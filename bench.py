"""Flagship benchmark: GPT causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline: measured tokens/sec vs the BASELINE.md north star proxy — an
8xA100 NCCL per-chip rate estimated at 40% MFU of A100 bf16 peak
(312 TFLOP/s) on the same model: tokens/s = 0.4*312e12 / flops_per_token.
(The reference publishes no numbers — BASELINE.md; this pins the ratio to
a reproducible formula instead.)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    if os.environ.get("BENCH_CPU") == "1":
        from paddle_tpu._testing import force_cpu
        force_cpu(pop_tpu=True)
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128)
        batch, seq, steps, warmup = 2, 128, 3, 1
    else:
        # GPT-1.3B class — the BASELINE.json north-star model ("GPT-3
        # 1.3B pretrain, per-chip tokens/sec"). h=2048, 16x128 heads
        # (head_dim 128 keeps the MXU lanes full), B4/S1024 with the
        # "names" remat policy fits v5e 16GB; measured 14.8k tok/s =
        # 1.007x the A100@40%MFU proxy. B8 exceeds memory (compile
        # fails); the smaller 350M config runs at 0.96-0.99x
        # (benchmarks/probes/_perf_sweep.py history).
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=1024)
        batch, seq, steps, warmup = 4, 1024, 8, 2
    # scan_unroll=num_layers (full layer unroll) measures +7% on v5e
    # (15.56k vs 14.55k tok/s — XLA schedules across layer boundaries);
    # its huge HLO occasionally trips the tunneled remote-compile
    # (HTTP 500, intermittent), so compile failures fall back to the
    # rolled loop instead of failing the bench. Partial unroll (4/8/12)
    # LOSES ~20% with fused CE — do not "compromise" on it.
    def build(unroll, moment_dtype=None, policy="names"):
        pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                              remat_policy=policy, scan_unroll=unroll,
                              param_dtype=jnp.bfloat16,
                              compute_dtype=jnp.bfloat16,
                              moment_dtype=moment_dtype)
        if policy == "names5":
            pcfg = ParallelConfig(
                dp=1, pp=1, tp=1, remat=True, remat_policy="names",
                remat_save_names=("attn_out", "ffn1", "qkv", "proj",
                                  "ffn2"),
                scan_unroll=unroll, param_dtype=jnp.bfloat16,
                compute_dtype=jnp.bfloat16, moment_dtype=moment_dtype)
        return setup(cfg, pcfg, seed=0, devices=jax.devices()[:1])

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # NOTE: sync via scalar readback (float(loss)), not block_until_ready —
    # the tunneled PJRT backend acks block_until_ready before the device
    # actually finishes; a host readback is the only true barrier there.
    #
    # Drift robustness (round 4): the tunnel's step time drifts up to
    # 18% intra-day (NOTES), so ONE timed window records whatever the
    # transport felt like at capture time. Run N windows and report the
    # BEST — the closest observable to the program's true cost under
    # transient contention — with every window's ms/step dumped to
    # stderr so a bad capture is diagnosable from the record.
    n_windows = 1 if on_cpu else max(
        1, int(os.environ.get("BENCH_WINDOWS", 3)))

    def timed(unroll, moment_dtype=None, policy="names"):
        mesh, params, opt_state, step = build(unroll, moment_dtype,
                                              policy)
        window_dts = []
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            for w in range(n_windows):
                t0 = time.perf_counter()
                for _ in range(steps):
                    params, opt_state, loss = step(params, opt_state,
                                                   (ids, ids))
                float(loss)
                window_dts.append(time.perf_counter() - t0)
        print(json.dumps({
            "rung": {"unroll": unroll, "policy": policy},
            "windows_ms_per_step": [round(d / steps * 1e3, 1)
                                    for d in window_dts],
        }), file=sys.stderr)
        return mesh, params, opt_state, step, min(window_dts)

    # Fallback ladder: the tunneled compile service intermittently (a)
    # 500s on the huge full-unroll HLO and (b) switches to strict AOT
    # hbm accounting under which the f32-moment program (19.2G est.)
    # no longer fits — bf16 moments (~15G) do, with loss parity proven
    # exact to 1e-6/30 steps (benchmarks/probes/_r3_moment_parity.py).
    # moments=None INHERITS the param dtype (bf16 here) — the exact
    # round-2 configuration all recorded numbers ran under (a round-3
    # f32-moment default briefly inflated the program by 5.2 GB and
    # masqueraded as a tunnel regression — see NOTES). bf16-vs-f32
    # moment parity: 1.45e-6 max rel dev over 30 steps measured,
    # asserted < 5e-3 (benchmarks/probes/_r3_moment_parity.py). Later rungs
    # trade throughput for memory headroom.
    attempts = [(cfg.num_layers, None, "names"),
                (1, None, "names"),
                (cfg.num_layers, None, "names5"),
                (1, None, "full")]
    if on_cpu:
        attempts = [(1, None, "names")]
    last = None
    for unroll, md, policy in attempts:
        if last is not None:
            # free the previous rung's pinned buffers OUTSIDE the
            # except block (active-exception state blocks collection)
            import gc
            gc.collect()
            jax.clear_caches()
        try:
            mesh, params, opt_state, step, dt = timed(unroll, md,
                                                      policy)
            break
        except Exception as e:
            # drop the traceback: its frames pin the failed rung's
            # device arrays (params+moments, ~13 GB) and would cascade
            # OOM into every later rung
            last = RuntimeError(
                f"all bench configs failed; last: {type(e).__name__}: "
                f"{e}")
            del e
            print(f"bench config (unroll={unroll}, moments="
                  f"{getattr(md, '__name__', md)}, {policy}) failed; "
                  "trying next", file=sys.stderr)
    else:
        raise last

    tokens_per_sec = batch * seq * steps / dt

    if os.environ.get("BENCH_LOSS_CURVE") == "1":
        # per-step scalar readback breaks async pipelining, so the
        # curve is sampled AFTER the timed window — and BEFORE the
        # extra-rung section frees the primary state (stderr only; the
        # stdout contract stays one JSON line)
        curve = []
        with mesh:
            for _ in range(5):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
                curve.append(round(float(loss), 6))
        print(json.dumps({"loss_curve_tail": curve}), file=sys.stderr)


    # ---- extra recorded rungs (round 5: the artifact must carry the
    # long-context + decode + input-pipeline capabilities, not just the
    # flagship config; VERDICT r4 weak #2). Each rung is best-effort —
    # a failure records an error string instead of killing the bench.
    # single home of the flops/MFU math: cost_model (shared with the
    # observability MFU gauge)
    from paddle_tpu.cost_model import TPU_SPECS as _SPECS
    from paddle_tpu.cost_model import gpt_flops_per_token as \
        _gpt_flops_per_token
    from paddle_tpu.cost_model import mfu as _cm_mfu

    V5E_PEAK = _SPECS["v5e"]["flops"]   # bf16 FLOP/s, one v5e chip

    class _SkipRung(Exception):
        pass

    def _mfu(toks_per_s, fpt):
        return round(_cm_mfu(toks_per_s, fpt, "v5e"), 4)

    rungs = {}
    want_rungs = os.environ.get("BENCH_RUNGS", "all")

    def _want(name):
        # BENCH_RUNGS: "all" (default), "none", or a comma list of rung
        # names (train_dataloader_fed,train_s2048,train_s4096,
        # decode_gpt1.3b_b8)
        return want_rungs == "all" or name in want_rungs.split(",")

    if not on_cpu and want_rungs != "none":
        import gc

        def _cleanup():
            gc.collect()
            jax.clear_caches()

        def _train_rung(name, c, b_, s_, n_steps=6, n_warm=2,
                        wins=2):
            pc = ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                remat_policy="names",
                                param_dtype=jnp.bfloat16,
                                compute_dtype=jnp.bfloat16)
            mesh_, p_, o_, st_ = setup(c, pc, seed=0,
                                       devices=jax.devices()[:1])
            ids_ = jnp.asarray(rng.randint(0, c.vocab_size, (b_, s_)))
            dts = []
            with mesh_:
                for _ in range(n_warm):
                    p_, o_, l_ = st_(p_, o_, (ids_, ids_))
                float(l_)
                for _w in range(wins):
                    t0 = time.perf_counter()
                    for _ in range(n_steps):
                        p_, o_, l_ = st_(p_, o_, (ids_, ids_))
                    float(l_)
                    dts.append(time.perf_counter() - t0)
            tps = b_ * s_ * n_steps / min(dts)
            fpt = _gpt_flops_per_token(c, s_)
            rungs[name] = {
                "tokens_per_sec": round(tps, 1),
                "mfu": _mfu(tps, fpt),
                "windows_ms_per_step": [round(d / n_steps * 1e3, 1)
                                        for d in dts]}

        # input-pipeline rung: the SAME flagship executable fed by the
        # real io.DataLoader (background prefetch) instead of a pinned
        # batch — proves the loader does not throttle the step
        # (VERDICT r4 item 8). Reuses the primary rung's compiled step.
        try:
            if not _want("train_dataloader_fed"):
                raise _SkipRung()
            import paddle_tpu as paddle

            class _Synth(paddle.io.Dataset):
                def __len__(self):
                    return 64

                def __getitem__(self, i):
                    r = np.random.RandomState(i)
                    a = r.randint(0, cfg.vocab_size,
                                  (seq,)).astype(np.int64)
                    return a, a

            # num_workers=1 engages the background-thread prefetch
            # branch (num_workers=0 takes the synchronous path and
            # would not exercise the buffered reader this rung is
            # meant to prove out)
            dl = paddle.io.DataLoader(_Synth(), batch_size=batch,
                                      shuffle=False, num_workers=1,
                                      prefetch_factor=2)
            n_dl = 0
            with mesh:
                # warm one loader batch through the step
                for xb, yb in dl:
                    params, opt_state, loss = step(
                        params, opt_state, (xb._data, yb._data))
                    break
                float(loss)
                t0 = time.perf_counter()
                for xb, yb in dl:
                    params, opt_state, loss = step(
                        params, opt_state, (xb._data, yb._data))
                    n_dl += 1
                float(loss)
                dl_dt = time.perf_counter() - t0
            dl_tps = batch * seq * n_dl / dl_dt
            rungs["train_dataloader_fed"] = {
                "tokens_per_sec": round(dl_tps, 1),
                "vs_pinned_batch": round(dl_tps / tokens_per_sec, 4)}
        except _SkipRung:
            pass
        except Exception as e:  # noqa: BLE001
            rungs["train_dataloader_fed"] = {
                "error": f"{type(e).__name__}: {e}"}


        # primary-rung state (params+moments, ~13 GB) is dead from here
        # on — free it BEFORE the long-context/decode rungs so they get
        # a clean chip (round-5 first capture: the dataloader rung ran
        # last, after clear_caches had dropped the hot executable, and
        # RESOURCE_EXHAUSTED'd; decode ran against 13 GB of pinned
        # stale state)
        del params, opt_state, step, mesh
        _cleanup()

        # long-context rungs: the NOTES-validated 350M-class model
        # (h1024/L24/heads8) at S=2048 and S=4096 — exercises the
        # attention-kernel dispatch chain (causal-skip at S=2048, the
        # q×kv-blocked flash kernel at S=4096).  Each rung records the
        # autotuner's winner for its attention shape, and train_s4096
        # records the s4096/s1024 MFU *ratio* — drift-robust against
        # the tunnel's intra-day transport weather, so the long-context
        # regression gate can pin the ratio rather than an absolute.
        flagship_mfu = _mfu(tokens_per_sec,
                            _gpt_flops_per_token(cfg, seq))
        for name, s_, b_ in (("train_s2048", 2048, 4),
                             ("train_s4096", 4096, 2)):
            if not _want(name):
                continue
            try:
                c = GPTConfig(vocab_size=50304, hidden_size=1024,
                              num_layers=24, num_heads=8,
                              max_seq_len=s_)
                # eager pre-measure so the winner is in the table when
                # the train step TRACES the dispatch (trace-time decide
                # is table-lookup-only — autotune.py header)
                attn_kernel = None
                try:
                    from paddle_tpu.ops.pallas import autotune as _at
                    hd = c.hidden_size // c.num_heads
                    attn_kernel = _at.measure(
                        (b_, s_, c.num_heads, hd), s_, jnp.bfloat16,
                        True)
                except Exception as ae:  # noqa: BLE001
                    attn_kernel = f"measure_error: {type(ae).__name__}"
                _cleanup()
                _train_rung(name, c, b_, s_)
                rungs[name]["attn_kernel"] = attn_kernel
                # drift-robust ratio rung for BOTH long-context seqs:
                # within-window vs the flagship S=1024 capture, the
                # quantity the perf gate pins (absolutes are
                # transport-weather; ISSUE 13)
                if flagship_mfu:
                    rungs[name]["mfu_ratio_vs_s1024"] = round(
                        rungs[name]["mfu"] / flagship_mfu, 4)
            except Exception as e:  # noqa: BLE001
                rungs[name] = {"error": f"{type(e).__name__}: {e}"}
            _cleanup()

        # serving rung: continuous batching with block decode — the
        # round-5 serving capability (overlapping request lifetimes
        # over the dense slot cache; one while_loop block program per
        # dispatch). Aggregate generated tok/s over a 16-request burst.
        try:
            if not _want("serve_cb_block16"):
                raise _SkipRung()
            import paddle_tpu as paddle
            from paddle_tpu.inference.decode import \
                ContinuousBatchingSession
            from paddle_tpu.models.llama import (LlamaConfig,
                                                 LlamaForCausalLM)
            paddle.seed(0)
            lcm = LlamaForCausalLM(LlamaConfig(
                vocab_size=32000, hidden_size=2048,
                intermediate_size=5504, num_layers=24, num_heads=16,
                num_kv_heads=16, max_seq_len=512))
            lcm.bfloat16()
            cbs = ContinuousBatchingSession(
                lcm, max_slots=8, max_length=512, decode_block=16)
            cb_rng = np.random.RandomState(0)
            cb_reqs = [(cb_rng.randint(0, 32000, (
                int(cb_rng.randint(32, 128)),)).astype(np.int32),
                int(cb_rng.randint(64, 128))) for _ in range(16)]
            for pr, bu in cb_reqs[:8]:
                cbs.submit(pr, bu)
            cbs.step()                                    # warm
            for pr, bu in cb_reqs[8:]:
                cbs.submit(pr, bu)
            # tokens emitted by the warm dispatch land before t0 —
            # exclude them from the timed count
            warm = {r.rid: len(r.tokens)
                    for r in list(cbs._running.values())
                    + list(cbs._done.values())}
            t0 = time.perf_counter()
            cb_out = cbs.run()
            cb_dt = time.perf_counter() - t0
            done_new = sum(
                len(v) - len(cb_reqs[i][0]) - warm.get(i, 0)
                for i, v in cb_out.items())
            rungs["serve_cb_block16"] = {
                "tokens_per_sec": round(done_new / cb_dt, 1),
                "requests": 16, "slots": 8}
            del cbs
        except _SkipRung:
            pass
        except Exception as e:  # noqa: BLE001
            rungs["serve_cb_block16"] = {
                "error": f"{type(e).__name__}: {e}"}

        # adversarial overload rung (ISSUE 14): the same serving model
        # under 2x-slot-capacity sustained offered load with a bounded
        # queue — admission control sheds the excess with fast
        # rejections while accepted requests keep flowing. Recorded as
        # a within-window ratio vs the unthrottled cb rung (absolutes
        # are transport weather), plus the accepted-request p99 from
        # the registry histogram.
        try:
            if not _want("serve_overload_2x"):
                raise _SkipRung()
            import paddle_tpu as paddle
            from paddle_tpu.inference.decode import (
                AdmissionRejected, ContinuousBatchingSession)
            if "lcm" not in locals():       # cb rung filtered out:
                from paddle_tpu.models.llama import (LlamaConfig,
                                                     LlamaForCausalLM)
                paddle.seed(0)
                lcm = LlamaForCausalLM(LlamaConfig(
                    vocab_size=32000, hidden_size=2048,
                    intermediate_size=5504, num_layers=24,
                    num_heads=16, num_kv_heads=16, max_seq_len=512))
                lcm.bfloat16()
            ov = ContinuousBatchingSession(
                lcm, max_slots=8, max_length=512, decode_block=16,
                max_queue=8)
            ov_rng = np.random.RandomState(1)
            plens, submit_t, finish_t = {}, {}, {}
            accepted = rejected = 0
            t0 = time.perf_counter()
            for _round in range(6):
                for _ in range(16):         # 2x the 8 slots, per round
                    pr = ov_rng.randint(0, 32000, (
                        int(ov_rng.randint(32, 128)),)).astype(np.int32)
                    bu = int(ov_rng.randint(64, 128))
                    try:
                        rid = ov.submit(pr, bu)
                        plens[rid] = pr.size
                        submit_t[rid] = time.perf_counter()
                        accepted += 1
                    except AdmissionRejected:
                        rejected += 1
                for rid in ov.step():
                    finish_t[rid] = time.perf_counter()
            # drain stepwise so completion times stay attributable to
            # THIS window (the global latency histogram also holds the
            # cb rung's samples)
            while ov._queue or ov._running or ov._pending:
                for rid in ov.step():
                    finish_t[rid] = time.perf_counter()
            ov_res = ov.results()
            ov_dt = time.perf_counter() - t0
            ov_gen = sum(len(r.ids) - plens[rid]
                         for rid, r in ov_res.items())
            hung = [rid for rid in plens if rid not in finish_t]
            lats = sorted(finish_t[rid] - submit_t[rid]
                          for rid in finish_t)
            p99 = lats[min(int(0.99 * len(lats)), len(lats) - 1)] \
                if lats else None
            rungs["serve_overload_2x"] = {
                "tokens_per_sec": round(ov_gen / ov_dt, 1),
                "accepted": accepted, "rejected": rejected,
                "hung": len(hung), "slots": 8, "max_queue": 8,
                "p99_request_latency_s":
                    round(p99, 4) if p99 is not None else None}
            ov.close()
            del ov, lcm
        except _SkipRung:
            pass
        except Exception as e:  # noqa: BLE001
            rungs["serve_overload_2x"] = {
                "error": f"{type(e).__name__}: {e}"}
        _cleanup()

        # decode rung: GPT-1.3B serving throughput (per-step decode
        # path, B8, bf16 weights) — the exact round-4 on-chip
        # configuration (benchmarks/probes/_decode_bench.py), recorded
        try:
            if not _want("decode_gpt1.3b_b8"):
                raise _SkipRung()
            import paddle_tpu as paddle
            from paddle_tpu.inference.decode import DecodeSession
            from paddle_tpu.models.gpt import GPTForCausalLM
            paddle.seed(0)
            gm = GPTForCausalLM(GPTConfig.gpt3_1p3b())
            gm.bfloat16()
            ds = DecodeSession(gm, 512)
            pids = paddle.to_tensor(
                rng.randint(0, 50304, (8, 128)).astype(np.int32))
            out_w = ds.generate(pids, max_new_tokens=4)   # warm
            np.asarray(out_w.numpy())                     # true barrier
            t0 = time.perf_counter()
            out_g = ds.generate(pids, max_new_tokens=64)
            # host readback barrier: block_until_ready is not a real
            # barrier on the tunneled transport (see header note)
            np.asarray(out_g.numpy())
            d_dt = time.perf_counter() - t0
            rungs["decode_gpt1.3b_b8"] = {
                "tokens_per_sec": round(8 * 64 / d_dt, 1)}
            del ds, gm
        except _SkipRung:
            pass
        except Exception as e:  # noqa: BLE001
            rungs["decode_gpt1.3b_b8"] = {
                "error": f"{type(e).__name__}: {e}"}
        _cleanup()

        # within-window serving ratio: continuous batching vs the
        # per-step decode path measured in the SAME capture — the
        # drift-robust rung the gate pins where the 129-480
        # transport-weather band makes the decode absolute gate nothing
        _cb = rungs.get("serve_cb_block16") or {}
        _dec = rungs.get("decode_gpt1.3b_b8") or {}
        if _cb.get("tokens_per_sec") and _dec.get("tokens_per_sec"):
            _cb["vs_decode_b8"] = round(
                _cb["tokens_per_sec"] / _dec["tokens_per_sec"], 4)
        # shed-not-collapse ratio: accepted throughput under 2x
        # overload vs the unthrottled cb rung in the SAME window — the
        # quantity the perf gate can pin (a collapsing session tends
        # toward 0; a shedding one stays near 1)
        _ov = rungs.get("serve_overload_2x") or {}
        if _ov.get("tokens_per_sec") and _cb.get("tokens_per_sec"):
            _ov["vs_cb_block16"] = round(
                _ov["tokens_per_sec"] / _cb["tokens_per_sec"], 4)

        # fault-resume rung (ISSUE 15): a mid-run crash injected at
        # the train.step chaos site, recovered by run_resilient +
        # FaultTolerantCheckpoint. Records time-to-recover (crash ->
        # first post-resume step) and post-resume throughput as a
        # within-window RATIO vs the same run uninterrupted — the
        # drift-robust quantity the perf gate can pin.
        fr_ck = base_ck = None
        try:
            if not _want("train_fault_resume"):
                raise _SkipRung()
            import tempfile

            import paddle_tpu as paddle
            from paddle_tpu import _chaos
            from paddle_tpu import io as pio
            from paddle_tpu import nn
            from paddle_tpu.distributed.elastic import run_resilient
            from paddle_tpu.hapi import (Callback, FaultTolerantCheckpoint,
                                         Model)
            from paddle_tpu.nn import functional as F_

            FV, FS, FB, FSTEPS, FKILL = 8192, 512, 4, 12, 6

            class _FRData(pio.Dataset):
                def __len__(self):
                    return FB * FSTEPS

                def __getitem__(self, i):
                    r = np.random.RandomState(i)
                    a = r.randint(0, FV, (FS,)).astype(np.int64)
                    return a, a

            class _FRLM(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.emb = nn.Embedding(FV, 256)
                    self.h = nn.Linear(256, 256)
                    self.act = nn.Tanh()
                    self.out = nn.Linear(256, FV)

                def forward(self, ids):
                    return self.out(self.act(self.h(self.emb(ids))))

            def _fr_loss(logits, labels):
                return F_.cross_entropy(logits.reshape([-1, FV]),
                                        labels.reshape([-1]))

            class _Clock(Callback):
                def __init__(self, sink):
                    self.sink = sink

                def on_train_batch_end(self, step, logs=None):
                    self.sink.append(time.perf_counter())

            def _fr_run(ck_root=None, sink=None):
                paddle.seed(0)
                net = _FRLM()
                fr_m = Model(net)
                fr_m.prepare(paddle.optimizer.SGD(
                    0.01, parameters=net.parameters()), _fr_loss)
                fr_dl = pio.DataLoader(_FRData(), batch_size=FB,
                                       shuffle=True, seed=7)
                fr_cbs = [_Clock(sink)] if sink is not None else []
                if ck_root is not None:
                    fr_cbs.append(FaultTolerantCheckpoint(
                        ck_root, every_n_steps=2, dataloader=fr_dl))
                fr_m.fit(fr_dl, epochs=1, verbose=0, callbacks=fr_cbs)

            # baseline runs with the SAME checkpoint callback (chaos
            # off): the ratio must isolate crash-recovery cost, not
            # conflate it with checkpoint-write overhead
            base_ck = tempfile.mkdtemp(prefix="bench_fault_base_")
            base_sink = []
            _fr_run(ck_root=base_ck, sink=base_sink)
            # steady-state steps/s, excluding the compile-laden first step
            base_sps = (len(base_sink) - 1) / \
                (base_sink[-1] - base_sink[0])

            fr_ck = tempfile.mkdtemp(prefix="bench_fault_resume_")
            os.environ[_chaos.ENV] = "on"
            _chaos.clear()
            _chaos.install("train.step", kind="error", times=1,
                           match=lambda c: c.get("step") == FKILL)
            crash_t = {}
            fr_sink = []
            run_resilient(lambda attempt: _fr_run(fr_ck, fr_sink),
                          max_restarts=2, backoff_s=0.05,
                          on_restart=lambda a, e:
                          crash_t.setdefault("t", time.perf_counter()))
            post = [t for t in fr_sink if t > crash_t["t"]]
            recover_s = post[0] - crash_t["t"]
            post_sps = (len(post) - 1) / (post[-1] - post[0]) \
                if len(post) > 1 else None
            rungs["train_fault_resume"] = {
                "killed_at_step": FKILL,
                "recover_s": round(recover_s, 3),
                "post_resume_tokens_per_sec":
                    round(post_sps * FB * FS, 1) if post_sps else None,
                "vs_uninterrupted":
                    round(post_sps / base_sps, 4) if post_sps else None}
        except _SkipRung:
            pass
        except Exception as e:  # noqa: BLE001
            rungs["train_fault_resume"] = {
                "error": f"{type(e).__name__}: {e}"}
        finally:
            # ALL cleanup here — a failed rung must not leave a live
            # chaos rule in the process-global registry or temp
            # checkpoint dirs on disk
            try:
                from paddle_tpu import _chaos as _chaos_cleanup
                _chaos_cleanup.clear()
            except Exception:  # noqa: BLE001
                pass
            os.environ.pop("PADDLE_TPU_CHAOS", None)
            import shutil as _shutil
            for _d in (fr_ck, base_ck):
                if _d:
                    _shutil.rmtree(_d, ignore_errors=True)
        _cleanup()

    # A100@40%MFU proxy for this exact model (6*N + 12*L*H*S attention)
    flops_per_token = _gpt_flops_per_token(cfg, seq)
    a100_baseline = 0.4 * 312e12 / flops_per_token
    out = {
        "metric": "gpt1.3b_train_tokens_per_sec_per_chip"
        if not on_cpu else "gpt_tiny_cpu_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / a100_baseline, 4),
        "best_of_windows": n_windows,
    }
    if not on_cpu:
        out["mfu"] = _mfu(tokens_per_sec, flops_per_token)
        out["assumed_peak_flops"] = V5E_PEAK
    if rungs:
        out["rungs"] = rungs

    # embed the registry snapshot that produced this capture, so the
    # ratio-based perf gate reads measurements and telemetry from ONE
    # artifact (attn.dispatch winners, bubble gauges, serving
    # counters — never re-derived from a different weather window)
    import paddle_tpu.observability as obs
    if obs.enabled():
        out["telemetry"] = {"ts": time.time(), "metrics": obs.dump()}

    # NOTES.md Round-6 verdict (stderr — the stdout contract stays one
    # JSON line): the next on-device capture resolves the blocked-flash
    # roofline question measured-or-refuted without manual spelunking
    s4096 = rungs.get("train_s4096") or {}
    if "mfu" in s4096:
        target = 0.62
        verdict = ("MEASURED >= target" if s4096["mfu"] >= target
                   else "BELOW target")
        print(f"[bench] s4096 roofline verdict: mfu={s4096['mfu']:.4f} "
              f"vs {target} target -> {verdict} (s4096/s1024 mfu ratio "
              f"{s4096.get('mfu_ratio_vs_s1024')}, "
              f"attn_kernel={s4096.get('attn_kernel')})",
              file=sys.stderr)
    elif not on_cpu and want_rungs != "none" and _want("train_s4096"):
        # only when the rung was REQUESTED — a deliberate BENCH_RUNGS
        # filter is not an unresolved verdict
        print("[bench] s4096 roofline verdict: UNRESOLVED (rung "
              f"errored: {s4096.get('error')})", file=sys.stderr)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
