"""Flagship benchmark: GPT causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline: measured tokens/sec vs the BASELINE.md north star proxy — an
8xA100 NCCL per-chip rate estimated at 40% MFU of A100 bf16 peak
(312 TFLOP/s) on the same model: tokens/s = 0.4*312e12 / flops_per_token.
(The reference publishes no numbers — BASELINE.md; this pins the ratio to
a reproducible formula instead.)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    if os.environ.get("BENCH_CPU") == "1":
        from paddle_tpu._testing import force_cpu
        force_cpu(pop_tpu=True)
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128)
        batch, seq, steps, warmup = 2, 128, 3, 1
    else:
        # GPT-1.3B class — the BASELINE.json north-star model ("GPT-3
        # 1.3B pretrain, per-chip tokens/sec"). h=2048, 16x128 heads
        # (head_dim 128 keeps the MXU lanes full), B4/S1024 with the
        # "names" remat policy fits v5e 16GB; measured 14.8k tok/s =
        # 1.007x the A100@40%MFU proxy. B8 exceeds memory (compile
        # fails); the smaller 350M config runs at 0.96-0.99x
        # (benchmarks/_perf_sweep.py history).
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=1024)
        batch, seq, steps, warmup = 4, 1024, 8, 2
    # scan_unroll=num_layers (full layer unroll) measures +7% on v5e
    # (15.56k vs 14.55k tok/s — XLA schedules across layer boundaries);
    # its huge HLO occasionally trips the tunneled remote-compile
    # (HTTP 500, intermittent), so compile failures fall back to the
    # rolled loop instead of failing the bench. Partial unroll (4/8/12)
    # LOSES ~20% with fused CE — do not "compromise" on it.
    def build(unroll, moment_dtype=None, policy="names"):
        pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                              remat_policy=policy, scan_unroll=unroll,
                              param_dtype=jnp.bfloat16,
                              compute_dtype=jnp.bfloat16,
                              moment_dtype=moment_dtype)
        if policy == "names5":
            pcfg = ParallelConfig(
                dp=1, pp=1, tp=1, remat=True, remat_policy="names",
                remat_save_names=("attn_out", "ffn1", "qkv", "proj",
                                  "ffn2"),
                scan_unroll=unroll, param_dtype=jnp.bfloat16,
                compute_dtype=jnp.bfloat16, moment_dtype=moment_dtype)
        return setup(cfg, pcfg, seed=0, devices=jax.devices()[:1])

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # NOTE: sync via scalar readback (float(loss)), not block_until_ready —
    # the tunneled PJRT backend acks block_until_ready before the device
    # actually finishes; a host readback is the only true barrier there.
    #
    # Drift robustness (round 4): the tunnel's step time drifts up to
    # 18% intra-day (NOTES), so ONE timed window records whatever the
    # transport felt like at capture time. Run N windows and report the
    # BEST — the closest observable to the program's true cost under
    # transient contention — with every window's ms/step dumped to
    # stderr so a bad capture is diagnosable from the record.
    n_windows = 1 if on_cpu else max(
        1, int(os.environ.get("BENCH_WINDOWS", 3)))

    def timed(unroll, moment_dtype=None, policy="names"):
        mesh, params, opt_state, step = build(unroll, moment_dtype,
                                              policy)
        window_dts = []
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            for w in range(n_windows):
                t0 = time.perf_counter()
                for _ in range(steps):
                    params, opt_state, loss = step(params, opt_state,
                                                   (ids, ids))
                float(loss)
                window_dts.append(time.perf_counter() - t0)
        print(json.dumps({
            "rung": {"unroll": unroll, "policy": policy},
            "windows_ms_per_step": [round(d / steps * 1e3, 1)
                                    for d in window_dts],
        }), file=sys.stderr)
        return mesh, params, opt_state, step, min(window_dts)

    # Fallback ladder: the tunneled compile service intermittently (a)
    # 500s on the huge full-unroll HLO and (b) switches to strict AOT
    # hbm accounting under which the f32-moment program (19.2G est.)
    # no longer fits — bf16 moments (~15G) do, with loss parity proven
    # exact to 1e-6/30 steps (benchmarks/_r3_moment_parity.py).
    # moments=None INHERITS the param dtype (bf16 here) — the exact
    # round-2 configuration all recorded numbers ran under (a round-3
    # f32-moment default briefly inflated the program by 5.2 GB and
    # masqueraded as a tunnel regression — see NOTES). bf16-vs-f32
    # moment parity: 1.45e-6 max rel dev over 30 steps measured,
    # asserted < 5e-3 (benchmarks/_r3_moment_parity.py). Later rungs
    # trade throughput for memory headroom.
    attempts = [(cfg.num_layers, None, "names"),
                (1, None, "names"),
                (cfg.num_layers, None, "names5"),
                (1, None, "full")]
    if on_cpu:
        attempts = [(1, None, "names")]
    last = None
    for unroll, md, policy in attempts:
        if last is not None:
            # free the previous rung's pinned buffers OUTSIDE the
            # except block (active-exception state blocks collection)
            import gc
            gc.collect()
            jax.clear_caches()
        try:
            mesh, params, opt_state, step, dt = timed(unroll, md,
                                                      policy)
            break
        except Exception as e:
            # drop the traceback: its frames pin the failed rung's
            # device arrays (params+moments, ~13 GB) and would cascade
            # OOM into every later rung
            last = RuntimeError(
                f"all bench configs failed; last: {type(e).__name__}: "
                f"{e}")
            del e
            print(f"bench config (unroll={unroll}, moments="
                  f"{getattr(md, '__name__', md)}, {policy}) failed; "
                  "trying next", file=sys.stderr)
    else:
        raise last

    tokens_per_sec = batch * seq * steps / dt

    if os.environ.get("BENCH_LOSS_CURVE") == "1":
        # per-step scalar readback breaks async pipelining, so the
        # curve is sampled AFTER the timed window (stderr only; the
        # stdout contract stays one JSON line)
        curve = []
        with mesh:
            for _ in range(5):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
                curve.append(round(float(loss), 6))
        print(json.dumps({"loss_curve_tail": curve}), file=sys.stderr)

    # A100@40%MFU proxy for this exact model (6*N + 12*L*H*S attention)
    h, L, s = cfg.hidden_size, cfg.num_layers, seq
    n_params = (cfg.vocab_size * h + cfg.max_seq_len * h
                + L * (12 * h * h + 13 * h) + 2 * h)
    flops_per_token = 6 * n_params + 12 * L * h * s
    a100_baseline = 0.4 * 312e12 / flops_per_token
    print(json.dumps({
        "metric": "gpt1.3b_train_tokens_per_sec_per_chip"
        if not on_cpu else "gpt_tiny_cpu_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / a100_baseline, 4),
        "best_of_windows": n_windows,
    }))


if __name__ == "__main__":
    main()
