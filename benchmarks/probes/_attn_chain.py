import sys; sys.path.insert(0, "/root/repo")
import time, math, functools
import numpy as np
import jax, jax.numpy as jnp

def timeit(name, fn, *args, steps=10, warmup=3):
    f = jax.jit(fn)
    try:
        out = None
        for _ in range(warmup):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        dt = (time.perf_counter() - t0) / steps
        print(f"{name}: {dt*1e3/24:.3f} ms/layer ({dt*1e3:.1f} ms/24)", flush=True)
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:100]}", flush=True)

key = jax.random.PRNGKey(0)
B, S, NH, D = 8, 1024, 16, 64
q = jax.random.normal(key, (B, NH, S, D), jnp.bfloat16)

from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes, flash_attention as fa)

def chain(att):
    def run(q):
        for _ in range(24):
            q = att(q)
        return q
    return run

timeit("pallas flash default x24", chain(
    lambda q: fa(q, q, q, causal=True, sm_scale=1/math.sqrt(D))), q)

blk = BlockSizes(block_q=512, block_k_major=512, block_k=512, block_b=1,
                 block_q_major_dkv=512, block_k_major_dkv=512,
                 block_k_dkv=512, block_q_dkv=512,
                 block_k_major_dq=512, block_k_dq=512, block_q_dq=512)
timeit("pallas flash blk512 x24", chain(
    lambda q: fa(q, q, q, causal=True, sm_scale=1/math.sqrt(D),
                 block_sizes=blk)), q)

def naive(q):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, q) / math.sqrt(D)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -1e9).astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, q)
timeit("naive x24", chain(naive), q)

qs = jnp.swapaxes(q, 1, 2)
def run_jnn(qs):
    for _ in range(24):
        qs = jax.nn.dot_product_attention(qs, qs, qs, is_causal=True)
    return qs
timeit("jax.nn.dpa x24", run_jnn, qs)

from jax.experimental.pallas.ops.tpu.splash_attention import (
    splash_attention_kernel as sk, splash_attention_mask as sm)
mask = sm.MultiHeadMask([sm.CausalMask((S, S))] * NH)
kernel = sk.make_splash_mha(mask, head_shards=1, q_seq_shards=1)
def run_splash(q):
    for _ in range(24):
        q = jax.vmap(kernel)(q * (1/math.sqrt(D)), q, q)
    return q
timeit("splash x24", run_splash, q)

# grad through 24-chain, flash vs naive
def g24(att):
    def run(q):
        def f(t):
            for _ in range(24):
                t = att(t)
            return t.astype(jnp.float32).sum()
        return jax.grad(f)(q)
    return run
timeit("flash default x24 fwd+bwd", g24(
    lambda q: fa(q, q, q, causal=True, sm_scale=1/math.sqrt(D))), q)
timeit("naive x24 fwd+bwd", g24(naive), q)
timeit("splash x24 fwd+bwd", g24(
    lambda t: jax.vmap(kernel)(t * (1/math.sqrt(D)), t, t)), q)
