import sys; sys.path.insert(0, "/root/repo")
import time, math
import numpy as np
import jax, jax.numpy as jnp

def timeit(name, fn, *args, steps=10, warmup=3):
    f = jax.jit(fn)
    try:
        out = None
        for _ in range(warmup):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        dt = (time.perf_counter() - t0) / steps
        print(f"{name}: {dt*1e3/24:.3f} ms/layer", flush=True)
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:100]}", flush=True)

key = jax.random.PRNGKey(0)
B, S, NH, D = 8, 1024, 16, 64
q = jax.random.normal(key, (B, NH, S, D), jnp.bfloat16)

from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes, flash_attention as fa)
blk = BlockSizes(block_q=512, block_k_major=512, block_k=512, block_b=1,
                 block_q_major_dkv=512, block_k_major_dkv=512,
                 block_k_dkv=512, block_q_dkv=512,
                 block_k_major_dq=512, block_k_dq=512, block_q_dq=512)

def g24(att):
    def run(q):
        def f(t):
            for _ in range(24):
                t = att(t)
            return t.astype(jnp.float32).sum()
        return jax.grad(f)(q)
    return run

timeit("flash blk512 x24 fwd+bwd", g24(
    lambda t: fa(t, t, t, causal=True, sm_scale=1/math.sqrt(D),
                 block_sizes=blk)), q)

mask = jnp.tril(jnp.ones((S, S), bool))
def naive_f32(t):
    s = jnp.einsum("bhqd,bhkd->bhqk", t, t) / math.sqrt(D)
    s = jnp.where(mask, s, -1e9).astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1).astype(t.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, t)
timeit("naive f32-softmax x24 fwd+bwd", g24(naive_f32), q)

def naive_bf16(t):
    s = jnp.einsum("bhqd,bhkd->bhqk", t, t) / math.sqrt(D)
    s = jnp.where(mask, s, jnp.asarray(-30000., s.dtype))
    m = jax.lax.stop_gradient(jnp.max(s, -1, keepdims=True))
    e = jnp.exp((s - m).astype(jnp.float32)).astype(t.dtype)
    p = e / jnp.sum(e.astype(jnp.float32), -1, keepdims=True).astype(t.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, t)
timeit("naive bf16-ish x24 fwd+bwd", g24(naive_bf16), q)

# naive under jax.checkpoint (as it will run inside remat block)
timeit("naive f32 x24 fwd+bwd remat", g24(
    lambda t: jax.checkpoint(naive_f32)(t)), q)
