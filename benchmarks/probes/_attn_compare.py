import sys; sys.path.insert(0, "/root/repo")
import time, math
import numpy as np
import jax, jax.numpy as jnp

def timeit(name, fn, *args, steps=20, warmup=5):
    f = jax.jit(fn)
    try:
        out = None
        for _ in range(warmup):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        dt = (time.perf_counter() - t0) / steps
        print(f"{name}: {dt*1e3:.2f} ms", flush=True)
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:100]}", flush=True)

key = jax.random.PRNGKey(0)
B, S, NH, D = 8, 1024, 16, 64
q = jax.random.normal(key, (B, NH, S, D), jnp.bfloat16)  # BHSD

# 1. pallas flash, library-default blocks
from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention as fa
def flash_default(q):
    return fa(q, q, q, causal=True, sm_scale=1/math.sqrt(D))
timeit("pallas flash (default blocks)", flash_default, q)

# 2. naive attention bf16
def naive(q):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, q) / math.sqrt(D)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -1e9).astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, q)
timeit("naive XLA attention", naive, q)

# 3. jax.nn.dot_product_attention (BSHD layout)
qs = jnp.swapaxes(q, 1, 2)
def jnn(qs):
    return jax.nn.dot_product_attention(qs, qs, qs, is_causal=True)
timeit("jax.nn.dot_product_attention", jnn, qs)

# 4. splash attention
try:
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm)
    mask = sm.CausalMask((S, S))
    mmask = sm.MultiHeadMask([mask] * NH)
    kernel = sk.make_splash_mha(mmask, head_shards=1, q_seq_shards=1)
    def splash(q):
        return jax.vmap(kernel)(q * (1/math.sqrt(D)), q, q)
    timeit("splash attention", splash, q)
except Exception as e:
    print("splash setup FAIL", repr(e)[:120])

# 5. fwd+bwd for best candidates
def naive_grad(q):
    return jax.grad(lambda t: naive(t).astype(jnp.float32).sum())(q)
timeit("naive fwd+bwd", naive_grad, q)
def flash_default_grad(q):
    return jax.grad(lambda t: flash_default(t).astype(jnp.float32).sum())(q)
timeit("pallas flash fwd+bwd (default)", flash_default_grad, q)
