import sys; sys.path.insert(0, "/root/repo")
import time, math
import numpy as np
import jax, jax.numpy as jnp

def timeit(name, fn, *args, steps=10, warmup=3):
    f = jax.jit(fn)
    try:
        out = None
        for _ in range(warmup):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        dt = (time.perf_counter() - t0) / steps
        print(f"{name}: {dt*1e3/24:.3f} ms/layer", flush=True)
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:100]}", flush=True)

key = jax.random.PRNGKey(0)
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes, flash_attention as fa)

def g24(att, q):
    def run(q):
        def f(t):
            for _ in range(24):
                t = att(t)
            return t.astype(jnp.float32).sum()
        return jax.grad(f)(q)
    return run, q

for NH, D in [(8, 128), (16, 64), (4, 256)]:
    B, S = 8, 1024
    q = jax.random.normal(key, (B, NH, S, D), jnp.bfloat16)
    blk = BlockSizes(block_q=512, block_k_major=512, block_k=512, block_b=1,
                     block_q_major_dkv=512, block_k_major_dkv=512,
                     block_k_dkv=512, block_q_dkv=512,
                     block_k_major_dq=512, block_k_dq=512, block_q_dq=512)
    att = lambda t: fa(t, t, t, causal=True, sm_scale=1/math.sqrt(D),
                       block_sizes=blk)
    run, qq = g24(att, q)
    timeit(f"flash H{NH} D{D} fwd+bwd", run, qq)
    def fwd24(t):
        for _ in range(24):
            t = att(t)
        return t
    timeit(f"flash H{NH} D{D} fwd", fwd24, q)
