import sys; sys.path.insert(0, "/root/repo")
import time, functools
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import (ParallelConfig, setup, loss_fn,
                                          forward, adamw_update)

cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                num_heads=16, max_seq_len=1024)
pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=True, remat_policy="dots",
                      param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16)
mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                      devices=jax.devices()[:1])
rng = np.random.RandomState(0)
B = 8
ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, 1024)))

def bench(name, fn, *args, steps=6, warmup=2):
    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.tree_util.tree_map(lambda a: np.asarray(jax.device_get(
        jax.tree_util.tree_leaves(a)[0].ravel()[0])), out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    # sync via tiny readback
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))
    dt = (time.perf_counter() - t0) / steps
    print(f"{name}: {dt*1000:.1f} ms/step -> {B*1024/dt:,.0f} tok/s", flush=True)
    return dt

with mesh:
    fwd = jax.jit(lambda p, i: loss_fn(p, (i, i), cfg, pcfg, mesh))
    bench("fwd+loss", fwd, params, ids)

    vg = jax.jit(lambda p, i: jax.value_and_grad(
        lambda q: loss_fn(q, (i, i), cfg, pcfg, mesh))(p))
    bench("fwd+bwd", vg, params, ids)

    bench("full step (donated)", step, params, opt_state, (ids, ids))

    # forward without the LM-head logsumexp (isolate vocab cost)
    fwd_only = jax.jit(lambda p, i: forward(p, i, cfg, pcfg, mesh).sum())
    bench("fwd logits only", fwd_only, params, ids)
