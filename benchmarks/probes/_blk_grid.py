import sys; sys.path.insert(0, "/root/repo")
import time, math, itertools
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes, flash_attention as fa)

key = jax.random.PRNGKey(0)
B, S, NH, D = 8, 1024, 8, 128
q = jax.random.normal(key, (B, NH, S, D), jnp.bfloat16)

def bench(blk, steps=8, warmup=2):
    att = lambda t: fa(t, t, t, causal=True, sm_scale=1/math.sqrt(D),
                       block_sizes=blk)
    def f(t):
        for _ in range(24):
            t = att(t)
        return t.astype(jnp.float32).sum()
    g = jax.jit(jax.grad(f))
    out = None
    for _ in range(warmup):
        out = g(q)
    np.asarray(jax.device_get(out.ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = g(q)
    np.asarray(jax.device_get(out.ravel()[0]))
    return (time.perf_counter() - t0) / steps / 24 * 1e3

best = None
for bq, bk, bdkv in [(512,512,512), (256,512,512), (512,256,512),
                     (512,512,256), (256,256,256), (1024,512,512),
                     (512,1024,512), (128,512,512), (512,512,128)]:
    try:
        blk = BlockSizes(
            block_q=min(bq,S), block_k_major=min(bk,S), block_k=min(bk,S),
            block_b=1,
            block_q_major_dkv=min(bdkv,S), block_k_major_dkv=min(bdkv,S),
            block_k_dkv=min(bdkv,S), block_q_dkv=min(bdkv,S),
            block_k_major_dq=min(bdkv,S), block_k_dq=min(bdkv,S),
            block_q_dq=min(bdkv,S))
        ms = bench(blk)
        print(f"bq={bq} bk={bk} bdkv={bdkv}: {ms:.3f} ms/layer", flush=True)
        if best is None or ms < best[0]:
            best = (ms, (bq, bk, bdkv))
    except Exception as e:
        print(f"bq={bq} bk={bk} bdkv={bdkv}: FAIL {str(e)[:80]}", flush=True)
print("BEST", best, flush=True)
