import sys; sys.path.insert(0, "/root/repo")
import time, math
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes, flash_attention as fa)

key = jax.random.PRNGKey(0)
B, S, NH, D = 8, 1024, 8, 128
q = jax.random.normal(key, (B, NH, S, D), jnp.bfloat16)

def bench(bb, steps=8, warmup=2):
    blk = BlockSizes(
        block_q=512, block_k_major=512, block_k=512, block_b=bb,
        block_q_major_dkv=512, block_k_major_dkv=512,
        block_k_dkv=512, block_q_dkv=512,
        block_k_major_dq=512, block_k_dq=512, block_q_dq=512)
    att = lambda t: fa(t, t, t, causal=True, sm_scale=1/math.sqrt(D),
                       block_sizes=blk)
    def f(t):
        for _ in range(24):
            t = att(t)
        return t.astype(jnp.float32).sum()
    g = jax.jit(jax.grad(f))
    try:
        out = None
        for _ in range(warmup):
            out = g(q)
        np.asarray(jax.device_get(out.ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = g(q)
        np.asarray(jax.device_get(out.ravel()[0]))
        dt = (time.perf_counter() - t0) / steps / 24 * 1e3
        print(f"block_b={bb}: {dt:.3f} ms/layer", flush=True)
    except Exception as e:
        print(f"block_b={bb}: FAIL {str(e)[:90]}", flush=True)

for bb in [1, 2, 4, 8]:
    bench(bb)
