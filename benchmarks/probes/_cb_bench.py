"""On-chip continuous-batching throughput probe (round 5).

Drives ContinuousBatchingSession on the real TPU with a stream of
overlapping requests (Poisson-ish staggered lengths/budgets) and
reports aggregate generated tokens/sec, vs the static-batch
DecodeSession on the same model as the ceiling.

Run ON TPU (no env overrides — let axon provide the chip):
    PYTHONPATH=/root/repo python benchmarks/probes/_cb_bench.py
"""
import os
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.decode import (ContinuousBatchingSession,
                                         DecodeSession)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

HID = int(os.environ.get("CB_HID", "1024"))
LAYERS = int(os.environ.get("CB_LAYERS", "12"))
SLOTS = int(os.environ.get("CB_SLOTS", "8"))
CAP = int(os.environ.get("CB_CAP", "512"))
NREQ = int(os.environ.get("CB_NREQ", "32"))

cfg = LlamaConfig(vocab_size=32000, hidden_size=HID,
                  intermediate_size=HID * 4 // 3 // 64 * 64 * 2,
                  num_layers=LAYERS, num_heads=HID // 64,
                  num_kv_heads=HID // 64, max_seq_len=CAP)
paddle.seed(0)
model = LlamaForCausalLM(cfg)
rng = np.random.RandomState(0)

pmax = max(CAP // 4, 8)
reqs = [(rng.randint(0, 32000, (int(rng.randint(pmax // 4, pmax)),))
         .astype(np.int32), int(rng.randint(pmax // 2, pmax)))
        for _ in range(NREQ)]
total_new = sum(b for _, b in reqs)

SYNC = int(os.environ.get("CB_SYNC", "8"))
BLK = int(os.environ.get("CB_BLOCK", "0")) or None
sess = ContinuousBatchingSession(model, max_slots=SLOTS,
                                 max_length=CAP, sync_every=SYNC,
                                 decode_block=BLK)
for ids, budget in reqs[:SLOTS]:
    sess.submit(ids, budget)
# warm both executables
sess.step()

for ids, budget in reqs[SLOTS:]:
    sess.submit(ids, budget)
t0 = time.perf_counter()
out = sess.run()
dt = time.perf_counter() - t0
done_new = sum(len(v) - len(reqs[i][0]) for i, v in out.items())
print(f"continuous batching: {done_new} tokens in {dt:.2f}s = "
      f"{done_new / dt:.1f} tok/s "
      f"(slots={SLOTS}, cap={CAP}, {NREQ} requests, "
      f"sync_every={SYNC}, block={BLK})")
print(f"executables: admit={sess.executable_counts()[0]} "
      f"decode={sess.executable_counts()[1]}")

# static-batch ceiling: same model, batch SLOTS, uniform length
ds = DecodeSession(model, CAP)
plen, gnew = max(CAP // 8, 4), max(CAP // 8, 4)
ids = paddle.to_tensor(rng.randint(0, 32000, (SLOTS, plen)))
ds.generate(ids, max_new_tokens=4)  # warm
t0 = time.perf_counter()
ds.generate(ids, max_new_tokens=gnew)
dt = time.perf_counter() - t0
print(f"static-batch ceiling: {SLOTS * gnew / dt:.1f} tok/s "
      f"(B={SLOTS}, {gnew} new)")
