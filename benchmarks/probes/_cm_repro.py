"""Minimal standalone reproducer of the collective-matmul-under-pp
Shardy wall (upstreamable verbatim).

The construct: a remat'd stage whose body opens an INNER tp-manual
shard_map (the ring collective matmul), differentiated inside an OUTER
pp-manual region's scan — the compiled-1F1B pattern of
paddle_tpu/parallel/pipeline_1f1b.py with
paddle_tpu/parallel/collective_matmul.py rings in the stage body.

Observed failure modes on jax 0.9.0 (which one fires depends on the
exact structure; the canary test
tests/test_collective_matmul.py::test_cm_under_pp_upstream_wall asserts
that at least one still does):
  (a) 'manual axes must come before free axes' — a rank-1 operand's
      vma {pp, tp} squashes both manual axes onto dim 0 of the inner
      region's operand;
  (b) 'operates on axis already bound by parent' — when the
      vma-widening pcast sits inside the inner region;
  (c) scan-carry vma mismatches between the pp-varying carry and the
      inner region's output.

Round-5 note: the CAPABILITY (ring collective matmul overlapping the
sp linears under pp>1) is delivered anyway via the manual-tp stage
body — tp manual at the SAME level as pp, no nested region, see
models/gpt_manual_tp.py — so this file tracks only the upstream
expressibility limit of the nested-region formulation used by the
GSPMD-auto-tp engines.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
     python benchmarks/probes/_cm_repro.py
Expected: a Shardy/vma error at trace/compile time (NOT a crash and
NOT success). Success means the upstream wall has cleared — then flip
gpt_hybrid._use_cm's pp==1 gate and planner.collective_matmul.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
try:  # neutralize this box's axon sitecustomize shim, if present
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    _f = _xb._get_backend_uncached
    if getattr(_f, "__name__", "") == "_axon_get_backend_uncached" \
            and _f.__closure__:
        _xb._get_backend_uncached = _f.__closure__[0].cell_contents
except Exception:  # noqa: BLE001
    pass
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def main():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("pp", "tp"))
    B, S, H = 2, 8, 8

    def vcast(t):
        def one(a):
            vma = getattr(jax.typeof(a), "vma", frozenset())
            return a if "pp" in vma else lax.pcast(a, ("pp",),
                                                   to="varying")
        return jax.tree_util.tree_map(one, t)

    def ring_row_matmul(x, w):
        """reduce_scatter(x @ w) as an INNER tp-manual ring — the
        nested region the wall is about."""
        def body(xl, wl):
            n = lax.axis_size("tp")
            idx = lax.axis_index("tp")
            m = xl.shape[0]
            s = m // n
            acc = jnp.zeros((s,) + xl.shape[1:-1] + (wl.shape[-1],),
                            xl.dtype)
            # widen the ring carry to the operands' union vma (the
            # in-tree ring's _zeros_like_vma fix) — without this the
            # shallower failure mode (c) fires first
            union = frozenset().union(
                *[getattr(jax.typeof(a), "vma", frozenset())
                  for a in (xl, wl)])
            need = tuple(union - getattr(jax.typeof(acc), "vma",
                                         frozenset()))
            if need:
                acc = lax.pcast(acc, need, to="varying")

            def step(acc, i):
                dest = jnp.mod(idx + (n - 1 - i), n)
                xs = lax.dynamic_slice_in_dim(xl, dest * s, s, 0)
                acc = acc + xs @ wl
                return lax.ppermute(
                    acc, "tp", [(j, (j + 1) % n) for j in range(n)]), None

            acc, _ = lax.scan(step, acc, jnp.arange(n - 1))
            dest = idx
            xs = lax.dynamic_slice_in_dim(xl, dest * s, s, 0)
            return acc + xs @ wl

        # inherit the ambient (pp-manual) mesh context like the
        # in-tree ring wrappers do (collective_matmul._smap): passing
        # the concrete mesh trips a SHALLOWER 'context mesh should
        # match' rejection first; omitting it reaches the documented
        # vma walls (a)-(c)
        return shard_map(body, axis_names={"tp"},
                         in_specs=(P(None, "tp"), P("tp", None)),
                         out_specs=P("tp", None))(x, w)

    @jax.checkpoint
    def stage(w, x):
        h = jax.nn.gelu(x.reshape(B * S, H))
        return ring_row_matmul(h, w).reshape(B, -1, H)[:, :S // 1] \
            .reshape(B, S, H)[:, :, :]

    def outer(blocks, x):
        w = blocks[0]

        def tick(carry, t):
            _, vjpfn = jax.vjp(
                lambda xx: stage(w, xx.reshape(B, S, H)).reshape(
                    B, S, H), carry)
            (dx,) = vjpfn(vcast(jnp.ones_like(carry)))
            return vcast(dx), None

        out, _ = lax.scan(tick, vcast(x), jnp.arange(3))
        return out[None]

    blocks = jnp.ones((2, H, H))
    x = jnp.ones((B, S, H))
    try:
        jax.jit(shard_map(outer, mesh=mesh, axis_names={"pp"},
                          in_specs=(P("pp"), P(None)),
                          out_specs=P("pp", None, None, None)))(
            blocks, x).block_until_ready()
    except Exception as e:  # noqa: BLE001
        print("WALL STILL PRESENT — rejection reproduced:")
        print(f"  {type(e).__name__}: {str(e)[:400]}")
        return 0
    print("WALL CLEARED: the nested tp-manual ring under a pp-manual "
          "vjp'd scan now compiles. Flip gpt_hybrid._use_cm's pp==1 "
          "gate and planner.collective_matmul.")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
