"""Decode tokens/s probe for the static-cache serving path.

Run on the real chip: `python benchmarks/probes/_decode_bench.py [size]`
size: tiny (default, CPU-safe) | 1.3b (GPT-1.3B-shaped, needs TPU HBM)

Reports prefill latency, per-token decode latency and tokens/s, and the
executable counts (must be 1 prefill + 1 decode after warmup).
"""
import sys
import time

sys.path.insert(0, ".")


def main():
    size = sys.argv[1] if len(sys.argv) > 1 else "tiny"
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.inference.decode import DecodeSession

    paddle.seed(0)
    if size == "1.3b":
        cfg = GPTConfig.gpt3_1p3b()
        B, S, new, cap = 8, 128, 128, 512
    else:
        cfg = GPTConfig.tiny()
        B, S, new, cap = 4, 16, 32, 64
    m = GPTForCausalLM(cfg)
    m.eval()
    if size == "1.3b":
        # serve in bf16 (the deployment precision)
        import jax.numpy as jnp
        for _, p in m.named_parameters():
            if jnp.issubdtype(p._data.dtype, jnp.floating):
                p._assign_array(p._data.astype(jnp.bfloat16))

    import os
    blk = int(os.environ.get("DECODE_BLOCK", "0")) or None
    sess = DecodeSession(m, cap, decode_block=blk)
    ids = paddle.randint(0, cfg.vocab_size, [B, S])

    t0 = time.perf_counter()
    out = sess.generate(ids, max_new_tokens=4)
    jax.block_until_ready(out._data)
    warm = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = sess.generate(ids, max_new_tokens=new)
    jax.block_until_ready(out._data)
    dt = time.perf_counter() - t0

    n_tok = B * new
    print(f"model={size} B={B} S={S} new={new} cap={cap} block={blk}")
    print(f"warmup(compile): {warm:.2f}s")
    print(f"generate: {dt*1e3:.1f}ms  "
          f"{n_tok/dt:.1f} tok/s  {dt/new*1e3:.2f} ms/step")
    print(f"executables (prefill, decode): {sess.executable_counts()}")


if __name__ == "__main__":
    main()
