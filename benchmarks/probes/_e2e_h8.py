import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

rng = np.random.RandomState(0)

def run(batch, heads, policy, steps=6, warmup=2):
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_heads=heads, max_seq_len=1024)
    pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                          remat_policy=policy,
                          param_dtype=jnp.bfloat16,
                          compute_dtype=jnp.bfloat16)
    try:
        mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                              devices=jax.devices()[:1])
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, 1024)))
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state, (ids, ids))
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = step(params, opt_state, (ids, ids))
            float(loss)
            dt = time.perf_counter() - t0
        tps = batch * 1024 * steps / dt
        print(f"b={batch} H={heads} {policy}: {tps:,.0f} tok/s loss={float(loss):.3f}", flush=True)
    except Exception as e:
        print(f"b={batch} H={heads} {policy}: FAIL {type(e).__name__} {str(e)[:90]}", flush=True)

run(8, 8, "names")
run(16, 8, "names")
run(8, 8, "dots")
run(16, 8, "dots")
