import time
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.flash_attention import flash_attention, flash_attention_maybe

b, s, h, d = 8, 1024, 16, 64
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
out = flash_attention_maybe(q, q, q, causal=True)
print("maybe returned:", None if out is None else out.shape)
try:
    out2 = flash_attention(q, q, q, causal=True)
    _ = np.asarray(out2[0,0,0,0])
    print("direct pallas OK", out2.shape)
except Exception as e:
    print("direct pallas FAIL:", type(e).__name__, str(e)[:300])

# time flash vs xla attention fwd+bwd
def xla_attn(q, k, v):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) / np.sqrt(d)
    iq = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
    ik = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
    logits = jnp.where((iq >= ik)[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, -1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)

for name, fn in [("xla", xla_attn), ("flash", lambda a,b_,c: flash_attention(a,b_,c,causal=True))]:
    try:
        loss = jax.jit(jax.grad(lambda q,k,v: fn(q,k,v).astype(jnp.float32).sum(), argnums=(0,)))
        g = loss(q,q,q); _ = np.asarray(g[0][0,0,0,0])
        t0 = time.perf_counter()
        for _ in range(10):
            g = loss(q,q,q)
        _ = np.asarray(g[0][0,0,0,0])
        dt = (time.perf_counter() - t0) / 10
        print(f"{name}: {dt*1e3:.2f} ms fwd+bwd")
    except Exception as e:
        print(f"{name} FAIL: {type(e).__name__} {str(e)[:200]}")
