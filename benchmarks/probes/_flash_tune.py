import time, math
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes, flash_attention as _fa

b, s, h, d = 8, 1024, 16, 64
rng = np.random.RandomState(0)
qt = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
READBACK = None

def timeit(blk, label, iters=50):
    global READBACK
    try:
        def attn(q, k, v):
            return _fa(q, k, v, causal=True, sm_scale=1/math.sqrt(d), block_sizes=blk)
        g = jax.jit(jax.grad(lambda q,k,v: attn(q,k,v).astype(jnp.float32).sum(), argnums=(0,1,2)))
        out = g(qt,qt,qt); _ = np.asarray(out[0][0,0,0,0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(qt,qt,qt)
        _ = np.asarray(out[0][0,0,0,0])
        dt = (time.perf_counter() - t0 - 0.071)/iters
        print(f"{label}: {dt*1e3:.2f} ms  ({0.12/dt:.0f} TFLOP/s)")
    except Exception as e:
        print(f"{label}: FAIL {type(e).__name__} {str(e)[:100]}")

def mk(bq, bk):
    return BlockSizes(block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
                      block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk, block_q_dkv=bq,
                      block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)

timeit(mk(512, 512), "q512 k512 (current)")
timeit(mk(1024, 512), "q1024 k512")
timeit(mk(1024, 1024), "q1024 k1024")
timeit(mk(512, 1024), "q512 k1024")
