import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax, jax.numpy as jnp
import paddle_tpu.models.gpt_hybrid as gh
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup
from jax.ad_checkpoint import checkpoint_name

rng = np.random.RandomState(0)

orig_block = gh._block

def flat_block(x, lp, cfg, pcfg, mesh):
    """_block with 2-D flattened GEMMs."""
    from paddle_tpu.models.gpt_hybrid import _layer_norm, _attend, _constrain
    from jax.sharding import PartitionSpec as P
    b, s, h = x.shape
    act_spec = P("dp", None, None)
    x = _constrain(x, act_spec, mesh)
    hres = x
    hx = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
    hx2 = hx.reshape(b * s, h)
    qkv = checkpoint_name((hx2 @ lp["qkv_w"] + lp["qkv_b"])
                          .reshape(b, s, -1), "qkv")
    q, k, v = jnp.split(qkv, 3, axis=-1)
    attn = checkpoint_name(_attend(q, k, v, cfg.num_heads), "attn_out")
    attn = (attn.reshape(b * s, h) @ lp["proj_w"] + lp["proj_b"]) \
        .reshape(b, s, h)
    x = hres + attn
    x = _constrain(x, act_spec, mesh)
    hres = x
    hx = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
    hx2 = hx.reshape(b * s, h)
    ff = (jax.nn.gelu(checkpoint_name(hx2 @ lp["fc1_w"] + lp["fc1_b"],
                                      "ffn1")) @ lp["fc2_w"]
          + lp["fc2_b"]).reshape(b, s, h)
    x = hres + ff
    return _constrain(x, act_spec, mesh)

def bench(name):
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_heads=8, max_seq_len=1024)
    pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                          remat_policy="names",
                          param_dtype=jnp.bfloat16,
                          compute_dtype=jnp.bfloat16)
    mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                          devices=jax.devices()[:1])
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 1024)))
    with mesh:
        for _ in range(2):
            params, opt_state, loss = step(params, opt_state, (ids, ids))
        float(loss)
        t0 = time.perf_counter()
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, (ids, ids))
        float(loss)
        dt = time.perf_counter() - t0
    print(f"{name}: {8*1024*8/dt:,.0f} tok/s loss={float(loss):.3f}", flush=True)

bench("baseline 3-D")
gh._block = flat_block
bench("flattened 2-D")
gh._block = orig_block
