# Fwd-only probe: Pallas block-tiled fused MLP (x@W1 -> gelu -> @W2,
# [M,4H] intermediate stays in VMEM) vs the XLA two-matmul chain.
import sys; sys.path.insert(0, "/root/repo")
import functools, time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

def _pl():
    from jax.experimental import pallas as pl
    return pl

def fused_mlp_fwd(x, w1, w2, bm=256, bn=256):
    pl = _pl()
    M, H = x.shape
    N = w1.shape[1]
    xblk = pl.BlockSpec((bm, H), lambda i, j: (i, 0))
    w1blk = pl.BlockSpec((H, bn), lambda i, j: (0, j))
    w2blk = pl.BlockSpec((bn, H), lambda i, j: (j, 0))
    oblk = pl.BlockSpec((bm, H), lambda i, j: (i, 0))

    def kernel(x_ref, w1_ref, w2_ref, o_ref):
        j = pl.program_id(1)
        mid = lax.dot_general(
            x_ref[...].astype(jnp.float32), w1_ref[...].astype(jnp.float32),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        mid = jax.nn.gelu(mid).astype(x_ref.dtype)
        contrib = lax.dot_general(
            mid, w2_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(j == 0)
        def _init():
            o_ref[...] = contrib.astype(o_ref.dtype)

        @pl.when(j > 0)
        def _acc():
            o_ref[...] += contrib.astype(o_ref.dtype)

    return pl.pallas_call(
        kernel, grid=(M // bm, N // bn),
        in_specs=[xblk, w1blk, w2blk],
        out_specs=oblk,
        out_shape=jax.ShapeDtypeStruct((M, H), jnp.float32),
    )(x, w1, w2)

def timeit(name, fn, *args, steps=30, warmup=5):
    f = jax.jit(fn)
    for _ in range(warmup): out = f(*args)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(steps): out = f(*args)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    dt = (time.perf_counter() - t0) / steps
    fl = 2 * 2 * M * H * N
    print(f"{name}: {dt*1e3:.3f} ms  {fl/dt/1e12:.1f} TF/s ({fl/dt/197e12*100:.0f}%)", flush=True)

if __name__ == "__main__":
    M, H = 4096, 2048
    N = 4 * H
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (M, H), jnp.bfloat16) * 0.3
    w1 = jax.random.normal(key, (H, N), jnp.bfloat16) * 0.02
    w2 = jax.random.normal(key, (N, H), jnp.bfloat16) * 0.02
    a = jax.jit(lambda x: jax.nn.gelu((x @ w1).astype(jnp.float32)).astype(jnp.bfloat16) @ w2)(x)
    b = jax.jit(lambda x: fused_mlp_fwd(x, w1, w2))(x)
    print("max err:", float(jnp.abs(a.astype(jnp.float32) - b).max()))
    timeit("xla chain", lambda x: jax.nn.gelu((x @ w1).astype(jnp.float32)).astype(jnp.bfloat16) @ w2, x)
    timeit("pallas fused", lambda x: fused_mlp_fwd(x, w1, w2), x)

# MEASURED (v5e, M=4096 H=2048 N=8192, bm=256/bn=256 — largest tiles
# that fit VMEM with double buffering): xla chain 4.724 ms vs pallas
# fused 5.538 ms. The fused version loses: 256-tile second matmul has
# weak MXU shape (K=bn) and the f32 o_ref += across 32 j-steps
# serializes. The [M,4H] HBM round-trip it saves (~0.16 ms/layer) is
# smaller than the tiling penalty. NEGATIVE RESULT — do not pursue
# without a smarter schedule (e.g. K-major accumulation in registers).
