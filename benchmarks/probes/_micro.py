import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax, jax.numpy as jnp

def timeit(name, fn, *args, flops=None, steps=20, warmup=5):
    f = jax.jit(fn)
    out = None
    for _ in range(warmup):
        out = f(*args)
    jax.block_until_ready(out)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = f(*args)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    dt = (time.perf_counter() - t0) / steps
    msg = f"{name}: {dt*1e3:.2f} ms"
    if flops:
        msg += f"  {flops/dt/1e12:.1f} TFLOP/s ({flops/dt/197e12*100:.0f}% of v5e peak)"
    print(msg, flush=True)

key = jax.random.PRNGKey(0)
B, S, H = 8, 1024, 1024
M = B * S

# chained matmul to avoid independent-dispatch issues: y = (x@W)@W2...
x = jax.random.normal(key, (M, H), jnp.bfloat16)
w1 = jax.random.normal(key, (H, 4*H), jnp.bfloat16)
w2 = jax.random.normal(key, (4*H, H), jnp.bfloat16)

def mlp_chain(x, w1, w2):
    for _ in range(24):
        x = jax.nn.gelu(x @ w1) @ w2
    return x
timeit("24x MLP h=1024", mlp_chain, x, w1, w2,
       flops=24*2*2*M*H*4*H)

wq = jax.random.normal(key, (H, 3*H), jnp.bfloat16)
def qkv_chain(x, w):
    for _ in range(24):
        x = (x @ w)[:, :H]
    return x
timeit("24x qkv h=1024", qkv_chain, x, wq, flops=24*2*M*H*3*H)

# big matmul sanity: [8192,8192]x[8192,8192]
a = jax.random.normal(key, (8192, 8192), jnp.bfloat16)
def big(a):
    return a @ a
timeit("8192^3 matmul", big, a, flops=2*8192**3)

# flash attention fwd
from paddle_tpu.ops.pallas.flash_attention import flash_attention
q = jax.random.normal(key, (B, S, 16, 64), jnp.bfloat16)
def attn_fwd(q):
    return flash_attention(q, q, q, causal=True)
timeit("flash fwd B8 S1024 H16 D64", attn_fwd, q,
       flops=4*B*16*S*S*64/2)  # causal half

# flash fwd+bwd
def attn_bwd(q):
    return jax.grad(lambda t: flash_attention(t, t, t, causal=True)
                    .astype(jnp.float32).sum())(q)
timeit("flash fwd+bwd", attn_bwd, q, flops=4*B*16*S*S*64/2*3.5)

# LM head + loss at bench shapes
wte = jax.random.normal(key, (50304, H), jnp.bfloat16)
hfin = jax.random.normal(key, (B, S, H), jnp.bfloat16)
tgt = jax.random.randint(key, (B, S-1), 0, 50304)
def lm_loss(h, w, t):
    logits = jnp.einsum("bsh,vh->bsv", h, w)[:, :-1].astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - picked)
timeit("LM head + CE loss", lm_loss, hfin, wte, tgt,
       flops=2*B*S*H*50304)
def lm_loss_grad(h, w, t):
    return jax.grad(lm_loss, argnums=(0, 1))(h, w, t)[0]
timeit("LM head + CE fwd+bwd", lm_loss_grad, hfin, wte, tgt,
       flops=3*2*B*S*H*50304)
