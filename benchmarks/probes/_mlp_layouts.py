import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax, jax.numpy as jnp

def timeit(name, fn, *args, steps=10, warmup=3, flops=None):
    f = jax.jit(fn)
    out = None
    for _ in range(warmup):
        out = f(*args)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = f(*args)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    dt = (time.perf_counter() - t0) / steps
    msg = f"{name}: {dt*1e3:.2f} ms"
    if flops:
        msg += f" {flops/dt/1e12:.0f} TF/s ({flops/dt/197e12*100:.0f}%)"
    print(msg, flush=True)

key = jax.random.PRNGKey(0)
M, H = 8192, 1024
x = jax.random.normal(key, (M, H), jnp.bfloat16)
w1 = jax.random.normal(key, (H, 4*H), jnp.bfloat16)
w2 = jax.random.normal(key, (4*H, H), jnp.bfloat16)
FL = 24*2*2*M*H*4*H

def mlp_gelu(x, w1, w2):
    for _ in range(24):
        x = jax.nn.gelu(x @ w1) @ w2
    return x
timeit("gelu(tanh)", mlp_gelu, x, w1, w2, flops=FL)

def mlp_relu(x, w1, w2):
    for _ in range(24):
        x = jax.nn.relu(x @ w1) @ w2
    return x
timeit("relu", mlp_relu, x, w1, w2, flops=FL)

def mlp_nogelu(x, w1, w2):
    for _ in range(24):
        x = (x @ w1) @ w2
    return x
timeit("no-activation", mlp_nogelu, x, w1, w2, flops=FL)

# 3-D batch layout like the model uses [B,S,H]
x3 = x.reshape(8, 1024, H)
def mlp3(x, w1, w2):
    for _ in range(24):
        x = jax.nn.gelu(x @ w1) @ w2
    return x
timeit("gelu 3-D [8,1024,H]", mlp3, x3, w1, w2, flops=FL)
