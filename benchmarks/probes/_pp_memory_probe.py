"""Peak-memory probe: compiled GPipe (jax.grad over the forward
pipeline) vs compiled 1F1B (parallel/pipeline_1f1b) at pp=4, M=8 —
the VERDICT round-1 item-6 measurement.

Run on the 8-virtual-device CPU mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/probes/_pp_memory_probe.py [M] [HID]

Reports XLA's compiled temp-buffer sizes (memory_analysis()) per
variant, plus the analytic live-activation counts from the schedule
descriptors. The GPipe backward is grad-of-scan: XLA must keep the
per-tick stage inputs for all M+N-1 ticks alive across the whole
backward; 1F1B's explicit interleave keeps a 2N-1-deep ring instead,
so its activation term is flat in M.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from paddle_tpu._testing import unshim_axon
    unshim_axon()
except Exception:
    pass

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402
from jax.experimental.shard_map import shard_map  # noqa: E402

from paddle_tpu.parallel.pipeline import (pipeline_apply,  # noqa: E402
                                          stack_stage_params)
from paddle_tpu.parallel.pipeline_1f1b import (  # noqa: E402
    compiled_1f1b_schedule, pipeline_train_1f1b)
from paddle_tpu.parallel.pp_schedule import schedule_fthenb  # noqa: E402

N = 4


def build(m, hid):
    rng = np.random.RandomState(0)
    stages = [{"w1": jnp.asarray(rng.randn(hid, hid) * 0.02, jnp.float32),
               "w2": jnp.asarray(rng.randn(hid, hid) * 0.02, jnp.float32)}
              for _ in range(N)]
    mb = jnp.asarray(rng.randn(m, 4, 128, hid) * 0.1, jnp.float32)
    stacked = stack_stage_params(stages)
    mesh = Mesh(np.asarray(jax.devices()[:N]), ("pp",))
    return stacked, mb, mesh


def stage_fn(params, x):
    return jnp.tanh(x @ params["w1"]) @ params["w2"] + x


def gpipe_grad_fn(stacked, mb, mesh):
    specs = jax.tree_util.tree_map(lambda _: P("pp"), stacked)

    def loss(stacked, mb):
        def body(stacked, mb):
            out = pipeline_apply(jax.checkpoint(stage_fn), stacked, mb)
            return out
        out = shard_map(body, mesh=mesh, in_specs=(specs, P(None)),
                        out_specs=P(None))(stacked, mb)
        return jnp.mean(out ** 2)

    return jax.jit(jax.grad(loss))


def f1b_fn(stacked, mb, mesh):
    specs = jax.tree_util.tree_map(lambda _: P("pp"), stacked)

    def body(stacked, mb):
        def last_grad(y, _hp, _mb_idx):
            l, dy = jax.value_and_grad(
                lambda y_: jnp.mean(y_ ** 2) * mb.shape[0])(y)
            return l / mb.shape[0], dy / mb.shape[0], None
        loss, grads, _, _ = pipeline_train_1f1b(
            stage_fn, stacked, mb, last_grad)
        return loss, grads

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(specs, P(None)),
                             out_specs=(P(), specs)))


def mem_stats(jitted, *args):
    compiled = jitted.lower(*args).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        return None
    return {"temp_MB": ma.temp_size_in_bytes / 2**20,
            "arg_MB": ma.argument_size_in_bytes / 2**20,
            "out_MB": ma.output_size_in_bytes / 2**20}


def main():
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    hid = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    stacked, mb, mesh = build(m, hid)
    act_mb = mb[0].size * 4 / 2**20

    g = gpipe_grad_fn(stacked, mb, mesh)
    s_g = mem_stats(g, stacked, mb)
    f = f1b_fn(stacked, mb, mesh)
    s_f = mem_stats(f, stacked, mb)

    print(f"pp={N} M={m} hid={hid} per-microbatch activation "
          f"= {act_mb:.2f} MB")
    print(f"schedule peak activations: gpipe/FThenB="
          f"{schedule_fthenb(N, m).peak_activations()}  compiled-1F1B="
          f"{compiled_1f1b_schedule(N, m).peak_activations()}")
    print(f"gpipe grad-of-scan:  {s_g}")
    print(f"compiled 1F1B:       {s_f}")
    if s_g and s_f:
        win = s_g["temp_MB"] / max(s_f["temp_MB"], 1e-9)
        print(f"temp-memory ratio gpipe/1f1b = {win:.2f}x")


if __name__ == "__main__":
    main()
