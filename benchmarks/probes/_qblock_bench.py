import sys; sys.path.insert(0, "/root/repo")
import time, math
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.simple_attention2 import attention_bhsd
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes, flash_attention as fa)

key = jax.random.PRNGKey(0)
B, H, S, D = 4, 8, 2048, 128
q = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)

def timeit(name, fn, *args, steps=8, warmup=2):
    f = jax.jit(fn)
    try:
        out = None
        for _ in range(warmup):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        print(f"{name}: {(time.perf_counter()-t0)/steps/12*1e3:.3f} ms/layer", flush=True)
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:140]}", flush=True)

blk = BlockSizes(block_q=512, block_k_major=512, block_k=512, block_b=1,
                 block_q_major_dkv=512, block_k_major_dkv=512,
                 block_k_dkv=512, block_q_dkv=512,
                 block_k_major_dq=512, block_k_dq=512, block_q_dq=512)
ref = fa(q, q, q, causal=True, sm_scale=1/math.sqrt(D), block_sizes=blk)
mine = attention_bhsd(q, q, q, causal=True)
print("max diff:", float(jnp.max(jnp.abs(ref.astype(jnp.float32)-mine.astype(jnp.float32)))), flush=True)

def g12(att):
    def run(q):
        def f(t):
            for _ in range(12):
                t = att(t)
            return t.astype(jnp.float32).sum()
        return jax.grad(f)(q)
    return run

simple = lambda t: attention_bhsd(t, t, t, causal=True)
flash = lambda t: fa(t, t, t, causal=True, sm_scale=1/math.sqrt(D), block_sizes=blk)
timeit("qblock fwd+bwd S2048", g12(simple), q)
timeit("flash  fwd+bwd S2048", g12(flash), q)
