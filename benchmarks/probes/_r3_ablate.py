"""Round-3 perf ablation on the real chip: where does step time go?

Measures the full 1.3B step, then variants with attention / LM-head+CE
swapped for cheap stand-ins, giving wall-clock shares to target.
"""
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    batch, seq, steps, warmup = 4, 1024, 6, 2
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    def timed(tag, unroll=24):
        pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                 remat_policy="names",
                                 scan_unroll=unroll,
                                 param_dtype=jnp.bfloat16,
                                 compute_dtype=jnp.bfloat16)
        mesh, params, opt_state, step = GH.setup(
            cfg, pcfg, seed=0, devices=jax.devices()[:1])
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            dt = (time.perf_counter() - t0) / steps
        tok = batch * seq / dt
        print(f"{tag}: {dt*1e3:.1f} ms/step  {tok:.0f} tok/s")
        return dt

    base = timed("full")

    # ---- attention -> identity (shares stay comparable: same remat)
    orig_attend = GH._attend

    def no_attend(q, k, v, nh):
        return v
    GH._attend = no_attend
    try:
        no_attn = timed("no-attention")
    finally:
        GH._attend = orig_attend

    # ---- LM head + CE -> cheap mean loss
    orig_ce = GH._ce_from_hidden

    def cheap_ce(x, wte, labels, pcfg):
        return jnp.mean(x.astype(jnp.float32)) * 1e-6
    GH._ce_from_hidden = cheap_ce
    try:
        no_head = timed("no-lmhead-ce")
    finally:
        GH._ce_from_hidden = orig_ce

    print(f"attention share : {(base - no_attn) / base * 100:.1f}%")
    print(f"lm-head+CE share: {(base - no_head) / base * 100:.1f}%")


if __name__ == "__main__":
    main()
