"""Two-program grad accumulation throughput at k=4/8 (real chip)."""
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    seq = 1024
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, seq)))

    # bf16 moments: halves optimizer state (fits the grad accumulator
    # in HBM) — loss parity proven exact-to-1e-6 over 30 steps
    # (benchmarks/probes/_r3_moment_parity.py)
    pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                             remat_policy="names", scan_unroll=24,
                             param_dtype=jnp.bfloat16,
                             compute_dtype=jnp.bfloat16,
                             moment_dtype=jnp.bfloat16)
    mesh, params, opt_state, _ = GH.setup(cfg, pcfg, seed=0,
                                          devices=jax.devices()[:1])
    grad_step, apply_step = GH.build_accum_steps(cfg, pcfg, mesh)
    acc = GH.init_grad_accum(params)

    with mesh:
        # warmup/compile both programs
        acc, loss = grad_step(params, acc, (ids, ids))
        params, opt_state, acc = apply_step(params, opt_state, acc, 1)
        float(loss)
        for k in [4, 8]:
            outer = 3
            t0 = time.perf_counter()
            for _ in range(outer):
                for _ in range(k):
                    acc, loss = grad_step(params, acc, (ids, ids))
                params, opt_state, acc = apply_step(params, opt_state,
                                                    acc, k)
            float(loss)
            dt = (time.perf_counter() - t0) / outer
            tok = 4 * seq * k / dt
            print(f"k={k}: {dt*1e3:.1f} ms per k-window  {tok:.0f} "
                  f"tok/s  loss={float(loss):.4f}", flush=True)


if __name__ == "__main__":
    main()
