"""Two-program grad accumulation: memory-fitting variants (real chip)."""
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    seq = 1024
    rng = np.random.RandomState(0)

    import os
    sel = os.environ.get("VARIANT", "")
    variants = [
        ("B4/full", 4, "full", 24),
        ("B2/names", 2, "names", 24),
        ("B2/full", 2, "full", 24),
    ]
    variants = [v for v in variants if not sel or v[0] == sel]
    for tag, b, policy, unroll in variants:
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, seq)))
        pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                 remat_policy=policy,
                                 scan_unroll=unroll,
                                 param_dtype=jnp.bfloat16,
                                 compute_dtype=jnp.bfloat16,
                                 moment_dtype=jnp.bfloat16)
        try:
            mesh, params, opt_state, _ = GH.setup(
                cfg, pcfg, seed=0, devices=jax.devices()[:1])
            grad_step, apply_step = GH.build_accum_steps(cfg, pcfg, mesh)
            acc = GH.init_grad_accum(params)
            with mesh:
                acc, loss = grad_step(params, acc, (ids, ids))
                params, opt_state, acc = apply_step(params, opt_state,
                                                    acc, 1)
                float(loss)
                k, outer = 8, 2
                t0 = time.perf_counter()
                for _ in range(outer):
                    for _ in range(k):
                        acc, loss = grad_step(params, acc, (ids, ids))
                    params, opt_state, acc = apply_step(
                        params, opt_state, acc, k)
                float(loss)
                dt = (time.perf_counter() - t0) / outer
                tok = b * seq * k / dt
                print(f"{tag}: k={k} {dt*1e3:.0f} ms/window  "
                      f"{tok:.0f} tok/s  loss={float(loss):.4f}",
                      flush=True)
            del params, opt_state, acc, grad_step, apply_step
        except Exception as e:
            print(f"{tag}: failed {type(e).__name__}: {e}"[:160],
                  flush=True)


if __name__ == "__main__":
    main()
