"""Timing: bf16 Adam moments vs f32 on the real chip (1.3B bench)."""
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    batch, seq, steps, warmup = 4, 1024, 6, 2
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    results = {}
    for tag, md in [("bf16-moments", jnp.bfloat16),
                    ("f32-moments", jnp.float32),
                    ("bf16-moments#2", jnp.bfloat16),
                    ("f32-moments#2", jnp.float32)]:
        pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                 remat_policy="names", scan_unroll=24,
                                 param_dtype=jnp.bfloat16,
                                 compute_dtype=jnp.bfloat16,
                                 moment_dtype=md)
        try:
            mesh, params, opt_state, step = GH.setup(
                cfg, pcfg, seed=0, devices=jax.devices()[:1])
        except Exception as e:
            print(f"{tag}: setup/compile failed {type(e).__name__}",
                  flush=True)
            continue
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            dt = (time.perf_counter() - t0) / steps
        print(f"{tag}: {dt*1e3:.1f} ms/step  "
              f"{batch*seq/dt:.0f} tok/s  loss={float(loss):.4f}",
              flush=True)


if __name__ == "__main__":
    main()
