"""Flat-accum engine throughput on the real chip (k=8, 1.3B)."""
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    seq = 1024
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, seq)))

    for unroll, policy in ((24, 'names'), (1, 'names'), (1, 'full')):
        try:
            pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                     remat_policy=policy,
                                     scan_unroll=unroll,
                                     param_dtype=jnp.bfloat16,
                                     compute_dtype=jnp.bfloat16,
                                     moment_dtype=jnp.bfloat16)
            mesh = GH.build_mesh(pcfg, jax.devices()[:1])
            init_state, train_window, _ = GH.build_flat_accum_bench(
                cfg, pcfg, mesh)
            pf, m, v, acc = init_state(seed=0)
            k = 8
            chunks = [(ids, ids)] * k
            with mesh:
                pf, m, v, acc, loss = train_window(pf, m, v, acc,
                                                   chunks, 1, k)
                float(loss)
                t0 = time.perf_counter()
                outer = 3
                for w in range(outer):
                    pf, m, v, acc, loss = train_window(
                        pf, m, v, acc, chunks, 2 + w, k)
                float(loss)
                dt = (time.perf_counter() - t0) / outer
            tok = 4 * seq * k / dt
            print(f"unroll={unroll}/{policy} k={k}: {dt*1e3:.0f} "
                  f"ms/window  {tok:.0f} tok/s  "
                  f"loss={float(loss):.4f}", flush=True)
            break
        except Exception as e:
            print(f"unroll={unroll}/{policy}: failed "
                  f"{type(e).__name__}: {e}"[:160], flush=True)


if __name__ == "__main__":
    main()
