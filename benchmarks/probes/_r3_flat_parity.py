"""CPU parity: flat-accum window (k=1) == classic fused step."""
import os
import sys

sys.path.insert(0, ".")


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu._testing import force_cpu
    force_cpu(pop_tpu=True)
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32)
    pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=False,
                             param_dtype=jnp.float32,
                             compute_dtype=jnp.float32)
    mesh, params, opt_state, step = GH.setup(cfg, pcfg, seed=0,
                                             devices=jax.devices()[:1])
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (4, 32)))
    with mesh:
        ref_params, _, ref_loss = step(params, opt_state, (ids, ids))

    init_state, train_window, unflatten = GH.build_flat_accum_bench(
        cfg, pcfg, mesh)
    pf, m, v, acc = init_state(seed=0)
    with mesh:
        pf, m, v, acc, loss = train_window(pf, m, v, acc,
                                           [(ids, ids)], 1, 1)
    got = unflatten(pf)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    print("FLAT == CLASSIC (loss and updated params)")

    # k=2 matches a 2x-batch classic step
    ids2 = jnp.asarray(np.random.RandomState(1).randint(0, 256,
                                                        (8, 32)))
    mesh2, params2, opt2, step2 = GH.setup(cfg, pcfg, seed=0,
                                           devices=jax.devices()[:1])
    with mesh2:
        refp, _, _ = step2(params2, opt2, (ids2, ids2))
    pf, m, v, acc = init_state(seed=0)
    with mesh:
        pf, m, v, acc, loss = train_window(
            pf, m, v, acc,
            [(ids2[:4], ids2[:4]), (ids2[4:], ids2[4:])], 1, 2)
    got = unflatten(pf)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(refp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    print("k=2 WINDOW == 2x-BATCH CLASSIC STEP")


if __name__ == "__main__":
    main()
