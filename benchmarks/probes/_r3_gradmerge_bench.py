"""Throughput vs gradient_merge_steps on the real chip.

The AdamW update is bandwidth-bound (~25 ms/step, 9% at B4/S1024);
k-chunk compiled gradient merge pays it once per k microbatches —
a bigger-global-batch pretrain config (GPT-3 1.3B trained at ~1M-token
batches; B4 per chunk keeps activation memory unchanged).
"""
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    seq, steps, warmup = 1024, 4, 2
    rng = np.random.RandomState(0)

    import os
    for k in [int(x) for x in os.environ.get('KS', '1,2,4').split(',')]:
        batch = 4 * k
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
        import os
        unroll = int(os.environ.get("UNROLL", "1"))
        pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                 remat_policy="names",
                                 scan_unroll=unroll,
                                 gradient_merge_steps=k,
                                 param_dtype=jnp.bfloat16,
                                 compute_dtype=jnp.bfloat16)
        ok = False
        for attempt in range(3):
            try:
                mesh, params, opt_state, step = GH.setup(
                    cfg, pcfg, seed=0, devices=jax.devices()[:1])
                ok = True
                break
            except Exception as e:
                print(f"k={k} attempt {attempt}: "
                      f"{type(e).__name__}"[:120], flush=True)
                time.sleep(20)
        if not ok:
            continue
        try:
            pass
            with mesh:
                for _ in range(warmup):
                    params, opt_state, loss = step(params, opt_state,
                                                   (ids, ids))
                float(loss)
                t0 = time.perf_counter()
                for _ in range(steps):
                    params, opt_state, loss = step(params, opt_state,
                                                   (ids, ids))
                float(loss)
                dt = (time.perf_counter() - t0) / steps
            tok = batch * seq / dt
            print(f"k={k} (global batch {batch}): {dt*1e3:.1f} ms/step"
                  f"  {tok:.0f} tok/s  loss={float(loss):.4f}",
                  flush=True)
        except Exception as e:
            print(f"k={k}: failed {type(e).__name__}: {e}"[:200],
                  flush=True)


if __name__ == "__main__":
    main()
