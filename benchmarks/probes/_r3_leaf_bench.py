"""Leaf-accum engine: CPU parity then TPU throughput."""
import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def parity():
    from paddle_tpu._testing import force_cpu
    force_cpu(pop_tpu=True)
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32)
    pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=False,
                             param_dtype=jnp.float32,
                             compute_dtype=jnp.float32)
    mesh, params, opt_state, step = GH.setup(cfg, pcfg, seed=0,
                                             devices=jax.devices()[:1])
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (4, 32)))
    with mesh:
        refp, _, refl = step(params, opt_state, (ids, ids))
    init_state, train_window = GH.build_leaf_accum_bench(cfg, pcfg, mesh)
    p, m, v, acc = init_state(seed=0)
    with mesh:
        p, m, v, acc, loss = train_window(p, m, v, acc, [(ids, ids)],
                                          1, 1)
    np.testing.assert_allclose(float(loss), float(refl), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p),
                    jax.tree_util.tree_leaves(refp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    print("LEAF == CLASSIC")


def bench():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH
    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    seq = 1024
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (4, seq)))
    sel = os.environ.get("VARIANT", "")
    allv = (("24/names", 24, "names"), ("1/names", 1, "names"),
            ("1/full", 1, "full"))
    allv = [v for v in allv if not sel or v[0] == sel]
    for _tag, unroll, policy in allv:
        try:
            pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                     remat_policy=policy,
                                     scan_unroll=unroll,
                                     param_dtype=jnp.bfloat16,
                                     compute_dtype=jnp.bfloat16,
                                     moment_dtype=jnp.bfloat16)
            mesh = GH.build_mesh(pcfg, jax.devices()[:1])
            init_state, train_window = GH.build_leaf_accum_bench(
                cfg, pcfg, mesh)
            k = int(os.environ.get("K", "1"))
            if k == 1:
                p, m, v, acc = init_state.noacc(seed=0)
            else:
                p, m, v, acc = init_state(seed=0)
            chunks = [(ids, ids)] * k
            with mesh:
                p, m, v, acc, loss = train_window(p, m, v, acc, chunks,
                                                  1, k)
                float(loss)
                t0 = time.perf_counter()
                outer = 3
                for w in range(outer):
                    p, m, v, acc, loss = train_window(
                        p, m, v, acc, chunks, 2 + w, k)
                float(loss)
                dt = (time.perf_counter() - t0) / outer
            tok = 4 * seq * k / dt
            print(f"{unroll}/{policy} k={k}: {dt*1e3:.0f} ms/window  "
                  f"{tok:.0f} tok/s  loss={float(loss):.4f}",
                  flush=True)
            break
        except Exception as e:
            print(f"{unroll}/{policy}: failed {type(e).__name__}: "
                  f"{e}"[:160], flush=True)


if __name__ == "__main__":
    if os.environ.get("PARITY") == "1":
        os.environ["JAX_PLATFORMS"] = "cpu"
        parity()
    else:
        bench()
