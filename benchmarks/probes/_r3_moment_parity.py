"""Loss-curve parity: bf16 vs f32 Adam moments (CPU, medium config).

The numerics gate for the bf16-moment perf lever: same init, same
batches, 30 steps; report per-step relative deviation of the loss.
"""
import os
import sys

sys.path.insert(0, ".")


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu._testing import force_cpu
    force_cpu(pop_tpu=True)
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                    num_heads=4, max_seq_len=128)
    rng = np.random.RandomState(0)
    batches = [jnp.asarray(rng.randint(0, 512, (4, 128)))
               for _ in range(30)]

    curves = {}
    for tag, md in [("f32", jnp.float32), ("bf16", jnp.bfloat16)]:
        pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=False,
                                 fused_ce=True,
                                 param_dtype=jnp.float32,
                                 compute_dtype=jnp.float32,
                                 moment_dtype=md)
        mesh, params, opt_state, step = GH.setup(
            cfg, pcfg, seed=0, devices=jax.devices()[:1])
        losses = []
        with mesh:
            for ids in batches:
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
                losses.append(float(loss))
        curves[tag] = np.asarray(losses)
        print(f"{tag}: first={losses[0]:.5f} last={losses[-1]:.5f}",
              flush=True)
    rel = np.abs(curves["bf16"] - curves["f32"]) / np.abs(curves["f32"])
    print(f"max rel deviation over 30 steps: {rel.max():.2e}")
    print(f"mean rel deviation: {rel.mean():.2e}")
    # the acc-align harness tolerance is 2e-3 at 5 steps; hold the
    # bf16-moment drift to the same order across 30
    assert rel.max() < 5e-3, rel.max()
    print("PARITY OK")


if __name__ == "__main__":
    main()
