"""Optimizer-cost ablation + moment-dtype probe on the real chip."""
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    batch, seq, steps, warmup = 4, 1024, 6, 2
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    def timed(tag):
        pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                                 remat_policy="names", scan_unroll=24,
                                 param_dtype=jnp.bfloat16,
                                 compute_dtype=jnp.bfloat16)
        mesh, params, opt_state, step = GH.setup(
            cfg, pcfg, seed=0, devices=jax.devices()[:1])
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            dt = (time.perf_counter() - t0) / steps
        print(f"{tag}: {dt*1e3:.1f} ms/step  "
              f"{batch*seq/dt:.0f} tok/s", flush=True)
        return dt

    base = timed("full-adamw-f32moments")

    # ---- SGD-style update (no moment traffic at all)
    orig_update = GH.adamw_update

    def sgd_update(params, grads, opt_state, lr=3e-4, **kw):
        new_p = jax.tree_util.tree_map(
            lambda p, g: (p.astype(jnp.float32)
                          - lr * g.astype(jnp.float32)).astype(p.dtype),
            params, grads)
        return new_p, opt_state
    GH.adamw_update = sgd_update
    try:
        sgd = timed("sgd-update")
    finally:
        GH.adamw_update = orig_update

    # ---- bf16 moments (half the optimizer HBM traffic)
    orig_init = GH.adamw_init

    def bf16_init(params, pcfg, mesh, specs):
        st = orig_init(params, pcfg, mesh, specs)
        st["m"] = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), st["m"])
        st["v"] = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), st["v"])
        return st
    GH.adamw_init = bf16_init
    try:
        bf16m = timed("adamw-bf16-moments")
    finally:
        GH.adamw_init = orig_init

    print(f"optimizer share (adam vs sgd): "
          f"{(base - sgd) / base * 100:.1f}%", flush=True)
    print(f"bf16-moments saving: {(base - bf16m) / base * 100:.1f}%",
          flush=True)


if __name__ == "__main__":
    main()
