"""Spend bf16-moment memory savings on LESS rematerialization.

With f32 moments the static state is 13 GB of 15.75 and 'names' (3
saved tensors/layer) was the remat optimum. bf16 moments cut state to
7.8 GB; this probes whether the freed 5+ GB buys back the ~recompute
cost via richer save policies. Run one variant per process:
  VARIANT=names|names5|dots|nof32names  python benchmarks/probes/_r3_remat_budget.py
"""
import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    batch, seq, steps, warmup = 4, 1024, 6, 2
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    variant = os.environ.get("VARIANT", "names")
    kw = dict(moment_dtype=jnp.bfloat16)
    if variant == "names":
        kw.update(remat_policy="names")
    elif variant == "names5":
        kw.update(remat_policy="names",
                  remat_save_names=("attn_out", "ffn1", "qkv", "proj",
                                    "ffn2"))
    elif variant == "dots":
        kw.update(remat_policy="dots")
    elif variant == "nof32names":
        kw = dict(moment_dtype=jnp.float32, remat_policy="names")

    pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                             scan_unroll=24,
                             param_dtype=jnp.bfloat16,
                             compute_dtype=jnp.bfloat16, **kw)
    try:
        mesh, params, opt_state, step = GH.setup(
            cfg, pcfg, seed=0, devices=jax.devices()[:1])
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            dt = (time.perf_counter() - t0) / steps
        print(f"{variant}: {dt*1e3:.1f} ms/step  "
              f"{batch*seq/dt:.0f} tok/s", flush=True)
    except Exception as e:
        print(f"{variant}: failed {type(e).__name__}: {e}"[:200],
              flush=True)


if __name__ == "__main__":
    main()
