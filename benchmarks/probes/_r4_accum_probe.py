"""Round-4 probe: split-program gradient accumulation on the real chip.

MODE=classic : the k=1 flagship step (the bench's first rung), timed.
MODE=split   : build_accum_steps engine — k grad_step calls (acc
               donated) + one whole-tree apply_step per window. Programs
               stay bench-sized (the fused k-chunk scan 500s the tunnel
               compile helper — 3 strikes over rounds 3-4).

Run each mode in its OWN process (failed-probe locals pin HBM).
"""
import os
import sys
import time

sys.path.insert(0, ".")
import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    mode = os.environ.get("MODE", "classic")
    k = int(os.environ.get("K", "4"))
    windows = int(os.environ.get("WINDOWS", "3"))
    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    batch, seq = int(os.environ.get("B", "4")), 1024
    pcfg = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                             remat_policy="names", scan_unroll=1,
                             param_dtype=jnp.bfloat16,
                             compute_dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    if mode == "classic":
        mesh, params, opt_state, step = GH.setup(
            cfg, pcfg, seed=0, devices=jax.devices()[:1])
        with mesh:
            for _ in range(2):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
            float(loss)
            for w in range(windows):
                t0 = time.perf_counter()
                for _ in range(8):
                    params, opt_state, loss = step(params, opt_state,
                                                   (ids, ids))
                float(loss)
                dt = time.perf_counter() - t0
                print(f"classic w{w}: {dt/8*1e3:.1f} ms/step "
                      f"{batch*seq*8/dt:.0f} tok/s", flush=True)
        return

    # split engine
    mesh = GH.build_mesh(pcfg, jax.devices()[:1])
    with mesh:
        params = GH.init_params(cfg, pcfg, jax.random.PRNGKey(0))
        params, specs = GH.shard_params(params, mesh, cfg, pcfg)
        mspecs = GH.moment_specs(params, pcfg, specs)
        opt_state = GH.adamw_init(params, pcfg, mesh, specs,
                                  mspecs=mspecs)
        grad_step, apply_step = GH.build_accum_steps(
            cfg, pcfg, mesh, state_specs=(specs, mspecs))
        acc = GH.init_grad_accum(params)
        # warmup: one full window (compiles both programs)
        for i in range(k):
            acc, loss = grad_step(params, acc, (ids, ids))
            float(loss)
            print(f"warmup grad_step {i} ok", flush=True)
        params, opt_state, acc = apply_step(params, opt_state, acc, k)
        jax.tree_util.tree_leaves(params)[0].block_until_ready()
        float(loss)
        print("warmup apply_step ok", flush=True)
        for w in range(windows):
            t0 = time.perf_counter()
            for _ in range(2):          # 2 outer windows = 2k microbatches
                for _ in range(k):
                    acc, loss = grad_step(params, acc, (ids, ids))
                params, opt_state, acc = apply_step(params, opt_state,
                                                    acc, k)
            float(loss)
            dt = time.perf_counter() - t0
            n_mb = 2 * k
            print(f"split k={k} w{w}: {dt/n_mb*1e3:.1f} ms/microbatch "
                  f"{batch*seq*n_mb/dt:.0f} tok/s loss={float(loss):.4f}",
                  flush=True)


if __name__ == "__main__":
    main()
