"""E2E probe: hybrid causal-fwd attention vs 'simple' in the flagship
bench config (the only comparison that counts — isolated kernel wins
have lied before, NOTES round 3)."""
import sys, time
sys.path.insert(0, ".")
import numpy as np


def main():
    import jax, jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.ops.pallas import causal_attention as cak
    import os

    which = os.environ.get("ATTN", "simple")
    B = int(os.environ.get("B", "4"))
    policy = os.environ.get("POLICY", "names")
    if which == "hybrid":
        orig = fa.flash_attention_maybe

        def patched(q, k, v, causal=False, scale=None):
            if causal and q.shape[1] == k.shape[1]:
                bhsd = (q.shape[0], q.shape[2], q.shape[1], q.shape[3])
                # hybrid needs BOTH the strip forward and the
                # monolithic backward to fit — supported() alone
                # admits shapes whose hybrid path raises
                if cak.hybrid_supported(bhsd, q.dtype):
                    qt = jnp.swapaxes(q, 1, 2)
                    kt = jnp.swapaxes(k, 1, 2)
                    vt = jnp.swapaxes(v, 1, 2)
                    out = cak.attention_bhsd_hybrid(qt, kt, vt,
                                                    causal=True,
                                                    scale=scale)
                    return jnp.swapaxes(out, 1, 2)
            return orig(q, k, v, causal=causal, scale=scale)
        fa.flash_attention_maybe = patched

    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    kw = dict(dp=1, pp=1, tp=1, remat=True, scan_unroll=1,
              param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16)
    if policy == "names5":
        kw.update(remat_policy="names",
                  remat_save_names=("attn_out", "ffn1", "qkv", "proj",
                                    "ffn2"))
    elif policy == "names3s":
        kw.update(remat_policy="names",
                  remat_save_names=("attn_out",))
    else:
        kw.update(remat_policy=policy)
    pcfg = GH.ParallelConfig(**kw)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, 1024)))
    mesh, params, opt, step = GH.setup(cfg, pcfg, seed=0,
                                       devices=jax.devices()[:1])
    with mesh:
        for _ in range(2):
            params, opt, loss = step(params, opt, (ids, ids))
        float(loss)
        for w in range(3):
            t0 = time.perf_counter()
            for _ in range(8):
                params, opt, loss = step(params, opt, (ids, ids))
            float(loss)
            dt = time.perf_counter() - t0
            print(f"{which} B{B} {policy} w{w}: {dt/8*1e3:.1f} "
                  f"ms/step {B*1024*8/dt:.0f} tok/s "
                  f"loss={float(loss):.4f}", flush=True)


if __name__ == "__main__":
    main()
