"""Zero-bubble probe: compiled 1F1B vs ZBH1 vs ZB-V (ZBVPP) at pp=4,
M=8 on the same 8-layer tanh model — temp memory (memory_analysis) and
schedule-descriptor makespan/bubble, the VERDICT round-3 item-3 "Done"
measurements extended to the V schedule.

1F1B/ZBH1 run 4 stages x 2 layers; ZB-V runs the same 8 layers as 8
V-placed virtual stages (1 layer each). Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/probes/_r4_zb_probe.py [M] [HID]
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from paddle_tpu._testing import unshim_axon
    unshim_axon()
except Exception:
    pass

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from paddle_tpu.parallel.pipeline_1f1b import (  # noqa: E402
    compiled_1f1b_schedule, compiled_zbh1_schedule,
    compiled_zbvpp_schedule, pipeline_train_1f1b, pipeline_train_zbh1,
    pipeline_train_zbvpp)

N = 4


def mem_stats(jitted, *args):
    c = jitted.lower(*args).compile()
    ma = c.memory_analysis()
    return ma.temp_size_in_bytes


def main():
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    hid = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    mesh = Mesh(np.array(jax.devices()[:N]), ("pp",))
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(m, 2, hid).astype(np.float32))
    tgt = jnp.asarray(rng.randn(m, 2, hid).astype(np.float32))
    hw = jnp.asarray(rng.randn(hid, hid).astype(np.float32))
    W8 = jnp.asarray(rng.randn(8, hid, hid).astype(np.float32))

    def last_grad(y, hp, mb):
        def head_loss(hp_, y_):
            return jnp.mean((y_ @ hp_ - tgt[mb]) ** 2) / m
        l, (ghp, gy) = jax.value_and_grad(
            head_loss, argnums=(0, 1))(hp, y)
        return l, gy, ghp

    # 4 stages x 2 layers
    def stage2(w, x):
        return jnp.tanh(jnp.tanh(x @ w[0]) @ w[1])

    # 8 virtual stages x 1 layer
    def stage1(w, x):
        return jnp.tanh(x @ w)

    W42 = W8.reshape(N, 2, hid, hid)
    vidx = np.stack([np.arange(N), 2 * N - 1 - np.arange(N)], axis=1)
    Wzv = W8[vidx]                                   # [N, 2, h, h]

    def run(fn, stage):
        return shard_map(
            lambda W_, xs_, hw_: fn(stage, W_, xs_, last_grad,
                                    head_params=hw_),
            mesh=mesh, axis_names={"pp"},
            in_specs=(P("pp"), P(None), P(None)),
            out_specs=(P(), P("pp"), P(), P(None)))

    with mesh:
        j1 = jax.jit(run(pipeline_train_1f1b, stage2))
        jz = jax.jit(run(pipeline_train_zbh1, stage2))
        jv = jax.jit(run(pipeline_train_zbvpp, stage1))
        t1 = mem_stats(j1, W42, xs, hw)
        tz = mem_stats(jz, W42, xs, hw)
        tv = mem_stats(jv, Wzv, xs, hw)

        def timeit(j, W):
            import time
            j(W, xs, hw)[0].block_until_ready()     # warmup
            t0 = time.perf_counter()
            for _ in range(10):
                out = j(W, xs, hw)
            out[0].block_until_ready()
            return (time.perf_counter() - t0) / 10 * 1e3

        ms1, msz, msv = (timeit(j1, W42), timeit(jz, W42),
                         timeit(jv, Wzv))

    print(f"pp={N} M={m} hid={hid}  (same 8-layer model)")
    print(f"temp bytes: 1f1b={t1/1e6:.1f}MB zbh1={tz/1e6:.1f}MB "
          f"zbvpp={tv/1e6:.1f}MB")
    print(f"wall ms/step (8-dev CPU mesh): 1f1b={ms1:.1f} "
          f"zbh1={msz:.1f} zbvpp={msv:.1f}")
    s1 = compiled_1f1b_schedule(N, m)
    # honest fused durations for the lockstep 1F1B: F=1, B=3
    s1.durations = {"F": 1.0, "B": 3.0}
    mk1, bb1 = s1.simulate()
    mkz, bbz = compiled_zbh1_schedule(N, m).simulate()
    mkv, bbv = compiled_zbvpp_schedule(N, m).simulate()
    # zbvpp stages are half-size: scale its makespan to the same
    # per-layer unit (F unit there covers 1 layer, not 2)
    print(f"makespan (per-2-layer units): 1f1b={mk1} zbh1={mkz} "
          f"zbvpp={mkv/2:.1f}")
    print(f"bubble: 1f1b={bb1:.4f} zbh1={bbz:.4f} zbvpp={bbv:.4f}")
    print(f"peak live acts: 1f1b={s1.peak_activations()} "
          f"zbh1={compiled_zbh1_schedule(N, m).peak_activations()} "
          f"zbvpp={compiled_zbvpp_schedule(N, m).peak_activations()}")


if __name__ == "__main__":
    main()
