"""Round-5 probe: which collectives survive inside a lax.cond branch
whose predicate varies over 'pp' but is UNIFORM over 'tp'?

Round 4 established that GSPMD-auto tp collectives inside a cond-gated
pipeline phase deadlock (half the mesh waits in-branch, half at the
ring permute) — hence the zero-bubble collective-free-stage constraint.
This probe separates the failure axes:

  A. manual shard_map over {'pp','tp'}, EXPLICIT lax.psum('tp') inside
     the cond branch (tp-uniform predicate) + ppermute('pp') per tick
  B. manual over {'pp'} only, tp GSPMD-auto inside: a tp-sharded
     matmul inside the cond branch (the round-4 configuration)
  C. control: same as A with the psum hoisted OUT of the cond
  D. sp-style all_gather + psum_scatter inside the cond branch
  E. ppermute('tp') inside a pp-DIVERGENT cond branch — DEADLOCKS:
     unlike psum/all_gather/reduce_scatter (lowered with SUBGROUP
     replica_groups), ppermute lowers to ONE collective-permute whose
     source-target pairs span the WHOLE mesh (every pp row's tp pairs
     merged), so idle pp stages never arrive. This is why the ring
     collective matmuls are restricted to the lockstep 1F1B route and
     refused under the cond-gated zero-bubble schedules.

Each leg runs under a hard alarm; a leg that trips the alarm is
recorded as DEADLOCK rather than hanging the probe. Leg E additionally
takes the whole process down after printing (XLA's rendezvous
termination timeout LOG(FATAL)s) — run it last / expect a crash tail.
"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
xb._backend_factories.pop("axon", None)
xb._backend_factories.pop("tpu", None)
_f = xb._get_backend_uncached
if getattr(_f, "__name__", "") == "_axon_get_backend_uncached" \
        and _f.__closure__:
    xb._get_backend_uncached = _f.__closure__[0].cell_contents

import signal

import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Alarm(Exception):
    pass


def _with_alarm(fn, seconds=60):
    def handler(signum, frame):
        raise Alarm()
    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(seconds)
    try:
        return fn()
    except Alarm:
        return "DEADLOCK(alarm)"
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


devs = np.array(jax.devices()[:4]).reshape(2, 2)
mesh = Mesh(devs, ("pp", "tp"))
H = 8


def _v(axes, x):
    vma = getattr(jax.typeof(x), "vma", frozenset())
    need = tuple(a for a in axes if a not in vma)
    return lax.pcast(x, need, to="varying") if need else x


def leg_a():
    """Manual tp, explicit psum INSIDE cond (tp-uniform predicate).
    Row-parallel matmul: x sliced on cols locally, w row-shard local,
    partial product psum'd over tp in-branch."""
    def body(x, w):
        s = lax.axis_index("pp")
        tix = lax.axis_index("tp")

        def tick(c, t):
            def active():
                xl = lax.dynamic_slice_in_dim(c, tix * (H // 2),
                                              H // 2, 1)
                part = xl @ w                     # local shard matmul
                # psum over tp in-branch; cast back to tp-varying so
                # both branches carry the same vma type
                return _v(("pp", "tp"), lax.psum(part, "tp"))

            def idle():
                return _v(("pp", "tp"), jnp.zeros((H, H), c.dtype))

            y = lax.cond((t - s) >= 0, active, idle)
            y = lax.ppermute(y, "pp",
                             [(i, (i + 1) % 2) for i in range(2)])
            return y, None

        x = _v(("pp", "tp"), x)
        out, _ = lax.scan(tick, x, jnp.arange(4))
        return lax.psum(out, ("pp", "tp")) / 4

    x = jnp.ones((H, H), jnp.float32)
    w = jnp.ones((H, H), jnp.float32)
    fn = jax.jit(shard_map(
        body, mesh=mesh, axis_names={"pp", "tp"},
        in_specs=(P(), P("tp", None)), out_specs=P()))
    r = fn(x, w)
    r.block_until_ready()
    return f"OK sum={float(r.sum()):.0f}"


def leg_b():
    """tp GSPMD-auto inside pp-manual region, sharded matmul in cond
    (the round-4 configuration that deadlocked)."""
    def body(x):
        s = lax.axis_index("pp")

        def tick(c, t):
            def active():
                w = jnp.ones((H, H), c.dtype)
                y = c @ w
                return jax.lax.with_sharding_constraint(
                    y, NamedSharding(mesh, P(None, "tp")))

            def idle():
                return _v(("pp",), jnp.zeros((H, H), c.dtype))

            y = _v(("pp",), lax.cond((t - s) >= 0, active, idle))
            y = lax.ppermute(y, "pp",
                             [(i, (i + 1) % 2) for i in range(2)])
            return y, None

        x = _v(("pp",), x)
        out, _ = lax.scan(tick, x, jnp.arange(4))
        return lax.psum(out, "pp") / 2

    x = jnp.ones((H, H), jnp.float32)
    fn = jax.jit(shard_map(
        body, mesh=mesh, axis_names={"pp"},
        in_specs=(P(),), out_specs=P()))
    r = fn(x)
    r.block_until_ready()
    return f"OK sum={float(r.sum()):.0f}"


def leg_c():
    """Control: manual tp, psum hoisted OUT of the cond."""
    def body(x, w):
        s = lax.axis_index("pp")
        tix = lax.axis_index("tp")

        def tick(c, t):
            def active():
                xl = lax.dynamic_slice_in_dim(c, tix * (H // 2),
                                              H // 2, 1)
                return xl @ w

            def idle():
                return _v(("pp", "tp"), jnp.zeros((H, H), c.dtype))

            part = lax.cond((t - s) >= 0, active, idle)
            y = _v(("pp", "tp"), lax.psum(part, "tp"))  # unconditional
            y = lax.ppermute(y, "pp",
                             [(i, (i + 1) % 2) for i in range(2)])
            return y, None

        x = _v(("pp", "tp"), x)
        out, _ = lax.scan(tick, x, jnp.arange(4))
        return lax.psum(out, ("pp", "tp")) / 4

    x = jnp.ones((H, H), jnp.float32)
    w = jnp.ones((H, H), jnp.float32)
    fn = jax.jit(shard_map(
        body, mesh=mesh, axis_names={"pp", "tp"},
        in_specs=(P(), P("tp", None)), out_specs=P()))
    r = fn(x, w)
    r.block_until_ready()
    return f"OK sum={float(r.sum()):.0f}"


def leg_d():
    """sp-style collectives (all_gather fwd + psum_scatter) inside the
    cond branch — the sequence-parallel stage-body case."""
    def body(x, w):
        s = lax.axis_index("pp")

        tix = lax.axis_index("tp")

        def tick(c, t):
            def active():
                # c is seq-sharded [H/2, H]; gather, row-parallel
                # matmul on the local shard, reduce-scatter back
                full = lax.all_gather(c, "tp", axis=0, tiled=True)
                xl = lax.dynamic_slice_in_dim(jnp.tanh(full),
                                              tix * (H // 2), H // 2, 1)
                part = xl @ w                      # [H, H] partial
                return _v(("pp", "tp"),
                          lax.psum_scatter(part, "tp",
                                           scatter_dimension=0,
                                           tiled=True))  # [H/2, H]

            def idle():
                return _v(("pp", "tp"),
                          jnp.zeros((H // 2, H), c.dtype))

            y = lax.cond((t - s) >= 0, active, idle)
            y = lax.ppermute(y, "pp",
                             [(i, (i + 1) % 2) for i in range(2)])
            return y, None

        out, _ = lax.scan(tick, _v(("pp", "tp"), x), jnp.arange(4))
        return lax.psum(out, "pp") / 2

    x = jnp.ones((H, H), jnp.float32)
    w = jnp.ones((H, H), jnp.float32)
    fn = jax.jit(shard_map(
        body, mesh=mesh, axis_names={"pp", "tp"},
        in_specs=(P("tp", None), P("tp", None)),
        out_specs=P("tp", None)))
    r = fn(x, w)
    r.block_until_ready()
    return f"OK sum={float(r.sum()):.0f}"


def leg_f():
    """lax.all_to_all over a manual axis inside a pp-divergent cond —
    feasibility probe for zero-bubble x EP-MoE (the GShard dispatch).
    Expected to behave like the subgroup collectives (legs A/D), NOT
    like ppermute (leg E): all_to_all lowers with subgroup
    replica_groups, so tp-group-uniform predicates rendezvous."""
    def body(x):
        s = lax.axis_index("pp")

        def tick(c, t):
            def active():
                return _v(("pp", "tp"),
                          lax.all_to_all(c.reshape(2, H // 2, H),
                                         "tp", split_axis=0,
                                         concat_axis=1, tiled=False)
                          .reshape(H, H))

            def idle():
                return _v(("pp", "tp"), jnp.zeros((H, H), c.dtype))

            y = lax.cond(s == 0, active, idle)  # divergent over pp
            y = lax.ppermute(y, "pp",
                             [(i, (i + 1) % 2) for i in range(2)])
            return y, None

        out, _ = lax.scan(tick, _v(("pp", "tp"), x), jnp.arange(2))
        return lax.psum(out, ("pp", "tp")) / 4

    x = jnp.ones((H, H), jnp.float32)
    fn = jax.jit(shard_map(
        body, mesh=mesh, axis_names={"pp", "tp"},
        in_specs=(P(),), out_specs=P()))
    r = fn(x)
    r.block_until_ready()
    return f"OK sum={float(r.sum()):.0f}"


def leg_e():
    """ppermute over tp inside a pp-DIVERGENT cond: expected DEADLOCK
    (whole-mesh collective-permute lowering; see module docstring)."""
    def body(x):
        s = lax.axis_index("pp")

        def tick(c, t):
            def active():
                return _v(("pp", "tp"),
                          lax.ppermute(c, "tp",
                                       [(0, 1), (1, 0)]))

            def idle():
                return _v(("pp", "tp"), jnp.zeros((H, H), c.dtype))

            y = lax.cond(s == 0, active, idle)  # divergent over pp
            y = lax.ppermute(y, "pp",
                             [(i, (i + 1) % 2) for i in range(2)])
            return y, None

        out, _ = lax.scan(tick, _v(("pp", "tp"), x), jnp.arange(2))
        return lax.psum(out, ("pp", "tp")) / 4

    x = jnp.ones((H, H), jnp.float32)
    fn = jax.jit(shard_map(
        body, mesh=mesh, axis_names={"pp", "tp"},
        in_specs=(P(),), out_specs=P()))
    r = fn(x)
    r.block_until_ready()
    return f"OK sum={float(r.sum()):.0f} (unexpected: wall cleared?)"


if __name__ == "__main__":
    for name, leg in [("A manual-psum-in-cond", leg_a),
                      ("B gspmd-auto-in-cond", leg_b),
                      ("C psum-hoisted", leg_c),
                      ("D sp-gather-scatter-in-cond", leg_d),
                      ("F all_to_all-in-divergent-cond", leg_f),
                      ("E ppermute-in-divergent-cond", leg_e)]:
        try:
            r = _with_alarm(leg, 60)
        except Exception as e:  # noqa: BLE001
            r = f"ERROR {type(e).__name__}: {e}"
        print(f"{name}: {r}", flush=True)
