"""Round-5 de-risk: the full ZB-under-tp mechanics on a toy pipeline.

Checks, on a pp2 x tp2 manual shard_map:
  1. jax.vjp INSIDE a cond branch over a manual-tp stage body
     (matmul with tp-sharded weight + explicit psum) — the AD-inserted
     transpose psums land in-branch; does it trace/run/deadlock?
  2. pcast varying->unvarying legality for emitting tp-identical
     outputs through out_specs P().
  3. Grad parity vs a single-device oracle.

The toy: 2 pipeline stages, each stage y = psum(x @ W_local, tp)
(row-parallel with x column-sliced locally), run as a cond-gated
2-tick-per-phase mini schedule with ppermute hops; loss = sum(y_final).
"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
xb._backend_factories.pop("axon", None)
xb._backend_factories.pop("tpu", None)
_f = xb._get_backend_uncached
if getattr(_f, "__name__", "") == "_axon_get_backend_uncached" \
        and _f.__closure__:
    xb._get_backend_uncached = _f.__closure__[0].cell_contents

import numpy as np
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

devs = np.array(jax.devices()[:4]).reshape(2, 2)
mesh = Mesh(devs, ("pp", "tp"))
H = 8
M = 2   # microbatches


def _v(x, axes=("pp",)):
    """Cast varying over pp ONLY: stage-boundary values stay naturally
    tp-invarying (the in-stage psum strips tp-variance), so epilogue
    outputs can use P() out_specs without any demotion (jax has no
    varying->invarying pcast).  Grad leaves match their param's vma."""
    vma = getattr(jax.typeof(x), "vma", frozenset())
    need = tuple(a for a in axes if a not in vma)
    for a in need:
        x = lax.pcast(x, a, to="varying")
    return x


def _zeros_like_vma(p):
    """zeros with vma = {pp} + (tp iff the param leaf is tp-varying)."""
    vma = getattr(jax.typeof(p), "vma", frozenset())
    z = jnp.zeros(p.shape, p.dtype)
    axes = ("pp",) + (("tp",) if "tp" in vma else ())
    return lax.pcast(z, tuple(a for a in axes
                              if a not in getattr(jax.typeof(z), "vma",
                                                  frozenset())),
                     to="varying")


def stage(w_local, x):
    """Row-parallel: slice x cols by tp rank, matmul local shard, psum."""
    tix = lax.axis_index("tp")
    xl = lax.dynamic_slice_in_dim(x, tix * (H // 2), H // 2, 1)
    part = jnp.tanh(xl) @ w_local
    return lax.psum(part, "tp")


def pipe_body(ws, x0):
    """Cond-gated 2-stage pipeline with in-branch vjp (B phase) and
    in-branch param-vjp (W phase)."""
    s = lax.axis_index("pp")
    w = jax.tree_util.tree_map(lambda p: p[0], ws)   # my stage's W

    T = M + 2 * (2 - 1)   # 1f1b grid
    act0 = _v(jnp.zeros((H, H), jnp.float32))
    cot0 = _v(jnp.zeros((H, H), jnp.float32))
    stash0 = _v(jnp.zeros((3, H, H), jnp.float32))
    grads0 = _zeros_like_vma(w)
    dx0_buf0 = _v(jnp.zeros((M, H, H), jnp.float32))
    loss0 = _v(jnp.zeros(()))

    k = 3

    def tick(carry, t):
        act_in, cot_in, stash, grads, loss, dx0_buf = carry
        mf = t - s
        f_active = (mf >= 0) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        f_act = jnp.where(s == 0, x0[mf_c], act_in)

        y = lax.cond(f_active,
                     lambda: _v(stage(w, f_act)),
                     lambda: _v(jnp.zeros((H, H), jnp.float32)))
        stash = lax.dynamic_update_index_in_dim(
            stash, f_act, jnp.mod(t, k), 0)

        # last-stage loss seed
        is_last = s == 1
        loss = loss + jnp.where(is_last & f_active, jnp.sum(y), 0.0)
        dy_seed = jnp.ones((H, H), jnp.float32)
        cot = jnp.where(is_last, dy_seed, cot_in)

        mb = t - 2 * (2 - 1) + s
        b_active = (mb >= 0) & (mb < M)
        x_b = stash[jnp.mod(t - 2 * (2 - 1 - s), k)]

        def b_do():
            # cot is tp-invarying by construction, matching the stage
            # output's vma ({V:pp} — the in-stage psum strips tp)
            _, vjpx = jax.vjp(lambda xx: stage(w, xx), x_b)
            (dx,) = vjpx(cot)
            return _v(dx)

        dx = lax.cond(b_active, b_do,
                      lambda: _v(jnp.zeros((H, H), jnp.float32)))

        def w_do(g):
            _, vjpp = jax.vjp(lambda pp: stage(pp, x_b), w)
            (dw,) = vjpp(cot)
            return jax.tree_util.tree_map(
                lambda a, d: _zeros_like_vma(a) + a + d, g, dw)

        grads = lax.cond(b_active, w_do, lambda g: _v(g), grads)

        dx0_buf = lax.cond(
            (s == 0) & b_active,
            lambda buf: lax.dynamic_update_index_in_dim(
                buf, dx, jnp.clip(mb, 0, M - 1), 0),
            lambda buf: buf, dx0_buf)

        act_out = lax.ppermute(y, "pp", [(0, 1), (1, 0)])
        cot_out = lax.ppermute(dx, "pp", [(1, 0), (0, 1)])
        return (act_out, cot_out, stash, grads, loss, dx0_buf), None

    carry, _ = lax.scan(
        tick, (act0, cot0, stash0, grads0, loss0, dx0_buf0),
        jnp.arange(T))
    _, _, _, grads, loss, dx0_buf = carry
    # loss lives on the last pp stage only -> psum over pp; already
    # tp-invarying (never cast over tp)
    loss = lax.psum(loss, "pp")
    # dx0_buf nonzero only on s==0, so the pp psum just collects it
    dx0 = lax.psum(dx0_buf, "pp")
    # re-add the leading stage dim so out_specs P('pp', 'tp', None)
    # reassembles [pp, H, H]
    return loss, grads[None], dx0


def run():
    key = jax.random.PRNGKey(0)
    ws = jax.random.normal(key, (2, H // 2, H)) * 0.3  # [pp, H/tp(row), H]
    # full weights for oracle: [stage, H, H] where rows split over tp
    wfull = jax.random.normal(key, (2, H, H)) * 0.3
    x0 = jax.random.normal(jax.random.PRNGKey(1), (M, H, H))

    fn = jax.jit(shard_map(
        pipe_body, mesh=mesh, axis_names={"pp", "tp"},
        in_specs=(P("pp", "tp", None), P()),
        out_specs=(P(), P("pp", "tp", None), P())))
    loss, grads, dx0 = fn(wfull, x0)
    loss.block_until_ready()

    # oracle: sequential 2-stage forward on one device
    def oracle(wfull, x0):
        def stage_full(wf, x):
            return jnp.tanh(x) @ wf
        tot = 0.0
        for mbi in range(M):
            h = stage_full(wfull[0], x0[mbi])
            y = stage_full(wfull[1], h)
            tot = tot + jnp.sum(y)
        return tot

    oloss, (ogw, ogx) = jax.value_and_grad(oracle, argnums=(0, 1))(
        wfull, x0)
    print("loss", float(loss), "oracle", float(oloss))
    np.testing.assert_allclose(float(loss), float(oloss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ogw),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx0), np.asarray(ogx),
                               rtol=1e-4, atol=1e-5)
    print("PARITY OK — in-branch vjp over manual-tp stage works")


if __name__ == "__main__":
    import signal

    def bail(signum, frame):
        raise SystemExit("DEADLOCK(alarm)")
    signal.signal(signal.SIGALRM, bail)
    signal.alarm(120)
    run()
