"""Round-5 probe: wall-clock of the zero-bubble schedules UNDER tp=2
vs the GSPMD 1F1B engine at matched config (dp1 x pp4 x tp2, hid 512,
L8, M8, sp on) — the manual-tp analog of round 4's _r4_zb_probe.

CPU-mesh numbers are directional only (no MXU, no ICI), but they show
whether the cond-gating skip survives the manual-tp restructuring +
serialize_phases barriers, or the barriers eat the win.
"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 "
    "--xla_cpu_enable_concurrency_optimized_scheduler=false")
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
xb._backend_factories.pop("axon", None)
xb._backend_factories.pop("tpu", None)
_f = xb._get_backend_uncached
if getattr(_f, "__name__", "") == "_axon_get_backend_uncached" \
        and _f.__closure__:
    xb._get_backend_uncached = _f.__closure__[0].cell_contents

import time

import numpy as np
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models import gpt_hybrid as GH

cfg = GPTConfig(vocab_size=512, hidden_size=512, num_layers=8,
                num_heads=8, max_seq_len=128)

results = {}
for sched in ["1f1b", "zbh1", "zbvpp"]:
    pcfg = GH.ParallelConfig(
        dp=1, tp=2, pp=4, sp=True, microbatches=8,
        pp_schedule=sched, remat=True,
        param_dtype=jnp.float32, compute_dtype=jnp.float32,
        fused_ce=False)
    mesh = GH.build_mesh(pcfg)
    params = GH.init_params(cfg, pcfg, jax.random.PRNGKey(0))
    params, _ = GH.shard_params(params, mesh, cfg, pcfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, 128)))
    fn = jax.jit(lambda p, b: GH._train_grads_1f1b(p, b, cfg, pcfg,
                                                   mesh))
    with mesh:
        loss, grads = fn(params, (ids, ids))
        loss.block_until_ready()
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            loss, grads = fn(params, (ids, ids))
            loss.block_until_ready()
            times.append((time.perf_counter() - t0) * 1e3)
    results[sched] = (min(times), float(loss))
    print(f"{sched:6s}: best {min(times):8.1f} ms/step  "
          f"(all {['%.0f' % t for t in times]})  loss {float(loss):.4f}",
          flush=True)

r = results
print(f"\nzbh1/1f1b: {r['zbh1'][0] / r['1f1b'][0]:.3f}  "
      f"zbvpp/1f1b: {r['zbvpp'][0] / r['1f1b'][0]:.3f}")
