import sys; sys.path.insert(0, "/root/repo")
import time, sys
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                num_heads=16, max_seq_len=1024)
rng = np.random.RandomState(0)

def run(batch, remat, policy, steps=6, warmup=2):
    pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=remat,
                          remat_policy=policy,
                          param_dtype=jnp.bfloat16,
                          compute_dtype=jnp.bfloat16)
    try:
        mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                              devices=jax.devices()[:1])
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, 1024)))
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state, (ids, ids))
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = step(params, opt_state, (ids, ids))
            float(loss)
            dt = time.perf_counter() - t0
        tps = batch * 1024 * steps / dt
        print(f"batch={batch} remat={remat} policy={policy}: {tps:,.0f} tok/s", flush=True)
    except Exception as e:
        print(f"batch={batch} remat={remat} policy={policy}: FAIL {type(e).__name__} {str(e)[:120]}", flush=True)

for b, r, p in [(8, False, "full"), (16, False, "full"), (16, True, "dots"),
                (8, True, "dots"), (16, True, "full"), (32, True, "dots")]:
    run(b, r, p)
