import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                num_heads=8, max_seq_len=2048)
pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=True, remat_policy="names",
                      param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16)
mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                      devices=jax.devices()[:1])
rng = np.random.RandomState(0)
ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 2048)))
with mesh:
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, (ids, ids))
    float(loss)
    t0 = time.perf_counter()
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, (ids, ids))
    float(loss)
    dt = time.perf_counter() - t0
print(f"S=2048 b4: {4*2048*8/dt:,.0f} tok/s loss={float(loss):.3f}", flush=True)
