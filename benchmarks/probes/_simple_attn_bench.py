import sys; sys.path.insert(0, "/root/repo")
import time, math
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.ops.pallas.simple_attention import attention_bhsd
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes, flash_attention as fa)

key = jax.random.PRNGKey(0)
B, H, S, D = 8, 8, 1024, 128
q = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)

def timeit(name, fn, *args, steps=10, warmup=3):
    f = jax.jit(fn)
    try:
        out = None
        for _ in range(warmup):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
        dt = (time.perf_counter() - t0) / steps
        print(f"{name}: {dt*1e3/24:.3f} ms/layer", flush=True)
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:160]}", flush=True)

# numerics on-device first
blk = BlockSizes(block_q=512, block_k_major=512, block_k=512, block_b=1,
                 block_q_major_dkv=512, block_k_major_dkv=512,
                 block_k_dkv=512, block_q_dkv=512,
                 block_k_major_dq=512, block_k_dq=512, block_q_dq=512)
ref = fa(q, q, q, causal=True, sm_scale=1/math.sqrt(D), block_sizes=blk)
mine = attention_bhsd(q, q, q, causal=True)
err = float(jnp.max(jnp.abs(ref.astype(jnp.float32) - mine.astype(jnp.float32))))
print("max fwd diff vs flash:", err, flush=True)

def chain(att):
    def run(q):
        for _ in range(24):
            q = att(q)
        return q
    return run

def g24(att):
    def run(q):
        def f(t):
            for _ in range(24):
                t = att(t)
            return t.astype(jnp.float32).sum()
        return jax.grad(f)(q)
    return run

simple = lambda t: attention_bhsd(t, t, t, causal=True)
flash = lambda t: fa(t, t, t, causal=True, sm_scale=1/math.sqrt(D),
                     block_sizes=blk)
timeit("simple fwd x24", chain(simple), q)
timeit("flash  fwd x24", chain(flash), q)
timeit("simple fwd+bwd x24", g24(simple), q)
timeit("flash  fwd+bwd x24", g24(flash), q)
