import sys; sys.path.insert(0, "/root/repo")
import time, math, functools
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, bh):
    for hh in range(bh):
        q = q_ref[0, hh].astype(jnp.float32)
        k = k_ref[0, hh].astype(jnp.float32)
        v = v_ref[0, hh]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            sq = s.shape[0]
            iq = jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
            ik = jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
            s = jnp.where(iq >= ik, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        p = (p / l).astype(v.dtype)
        o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[0, hh] = o.astype(o_ref.dtype)

def attn(q, bh, steps=10, warmup=3):
    B, H, S, D = q.shape
    blk = pl.BlockSpec((1, bh, S, D), lambda i, j: (i, j, 0, 0))
    f = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=1/math.sqrt(D),
                          causal=True, bh=bh),
        grid=(B, H // bh),
        in_specs=[blk, blk, blk], out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype))
    def run(t):
        for _ in range(24):
            t = f(t, t, t)
        return t
    g = jax.jit(run)
    out = None
    for _ in range(warmup):
        out = g(q)
    np.asarray(jax.device_get(out.ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = g(q)
    np.asarray(jax.device_get(out.ravel()[0]))
    print(f"bh={bh}: {(time.perf_counter()-t0)/steps/24*1e3:.3f} ms/layer fwd", flush=True)

key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (8, 8, 1024, 128), jnp.bfloat16)
for bh in (1, 2):
    try:
        attn(q, bh)
    except Exception as e:
        print(f"bh={bh}: FAIL {str(e)[:120]}", flush=True)
