import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax, jax.numpy as jnp
import paddle_tpu.models.gpt_hybrid as gh
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup
from jax import lax
import functools

rng = np.random.RandomState(0)

def run(unroll, steps=8, warmup=2):
    # monkeypatch scan unroll
    orig = gh._stack_apply
    def patched(blocks, x, cfg, pcfg, mesh):
        def body(h, lp):
            fn = functools.partial(gh._block, cfg=cfg, pcfg=pcfg, mesh=mesh)
            if pcfg.remat:
                fn = jax.checkpoint(
                    fn, policy=jax.checkpoint_policies
                    .save_only_these_names("attn_out", "ffn1", "qkv"))
            return fn(h, lp), None
        out, _ = lax.scan(body, x, blocks, unroll=unroll)
        return out
    gh._stack_apply = patched
    try:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                        num_heads=8, max_seq_len=1024)
        pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=True,
                              remat_policy="names",
                              param_dtype=jnp.bfloat16,
                              compute_dtype=jnp.bfloat16)
        mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                              devices=jax.devices()[:1])
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 1024)))
        with mesh:
            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state, (ids, ids))
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, loss = step(params, opt_state, (ids, ids))
            float(loss)
            dt = time.perf_counter() - t0
        print(f"unroll={unroll}: {8*1024*steps/dt:,.0f} tok/s", flush=True)
    except Exception as e:
        print(f"unroll={unroll}: FAIL {type(e).__name__} {str(e)[:90]}", flush=True)
    finally:
        gh._stack_apply = orig

for u in [1, 2, 4, 24]:
    run(u)
