"""VPP vs 1F1B compiled temp-memory probe (VERDICT r3 item 5 evidence).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python benchmarks/probes/_vpp_memory_probe.py

Measured (CPU mesh, pp=4, M=8, h=256, L=32, S=128, remat off):
    1f1b: temp=96.73MB
    vpp2: temp=104.25MB
    vpp4: temp=94.71MB
Reading: the inner-lane-scan design bounds live vjp residuals to ONE
chunk (L/(pp*v) layers), but the stash grows to v rings of 2(nv-1)+1
microbatch inputs. The residual win beats the stash cost once chunks
are deep enough relative to the ring (vpp4 wins at 8 layers/device;
vpp2's 4-layer split does not at this activation size). VPP is the
right tool when per-device depth is large — exactly its Megatron role.
"""
import sys

sys.path.insert(0, ".")


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    cfg = GPTConfig(vocab_size=128, hidden_size=256, num_layers=32,
                    num_heads=4, max_seq_len=128)
    ids = np.random.RandomState(0).randint(0, 128, (8, 128))
    for tag, kw in [("1f1b", {}), ("vpp2", dict(vpp_chunks=2)),
                    ("vpp4", dict(vpp_chunks=4))]:
        pcfg = ParallelConfig(dp=1, pp=4, tp=1, microbatches=8,
                              pp_schedule="1f1b", remat=False,
                              fused_ce=False,
                              param_dtype=jnp.float32,
                              compute_dtype=jnp.float32, **kw)
        mesh, params, opt_state, step = setup(
            cfg, pcfg, seed=0, devices=jax.devices()[:4])
        with mesh:
            ma = step.lower(params, opt_state,
                            (ids, ids)).compile().memory_analysis()
            print(f"{tag}: temp={ma.temp_size_in_bytes / 2**20:.2f}MB")


if __name__ == "__main__":
    main()
