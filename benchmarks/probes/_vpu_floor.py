import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax, jax.numpy as jnp

def timeit(name, fn, *args, steps=10, warmup=3):
    f = jax.jit(fn)
    out = None
    for _ in range(warmup):
        out = f(*args)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = f(*args)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0]))
    dt = (time.perf_counter() - t0) / steps
    print(f"{name}: {dt*1e3/24:.3f} ms per 1/24", flush=True)

key = jax.random.PRNGKey(0)
# one layer's attention scores: [B=8, H=8, S=1024, S=1024] bf16
s = jax.random.normal(key, (8, 8, 1024, 1024), jnp.bfloat16)

def chain24(fn):
    def run(x):
        for _ in range(24):
            x = fn(x)
        return x
    return run

timeit("softmax f32 x24", chain24(
    lambda x: jax.nn.softmax(x.astype(jnp.float32), -1).astype(x.dtype)), s)
timeit("exp only x24", chain24(lambda x: jnp.exp(x)), s)
timeit("copy only x24", chain24(lambda x: x + 1), s)
