"""Workload 2 (BASELINE.json configs): BERT-base MLM fine-tune under
AMP O2 with GradScaler (reference: paddle.nn.TransformerEncoder + amp).
"""
import sys
import os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import argparse
import time

import numpy as np


def main(smoke=True, steps=10):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    cfg = BertConfig(vocab_size=512, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128,
                     max_seq_len=64) if smoke else BertConfig()
    model = BertForMaskedLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3 if smoke else 5e-5,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    # AMP O2: bf16 weights with fp32 master weights via decorate
    model, opt = paddle.amp.decorate(models=model, optimizers=opt,
                                     level="O2",
                                     dtype="bfloat16")
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10)
    lossf = nn.CrossEntropyLoss()

    rng = np.random.RandomState(0)
    B, S = (4, 32) if smoke else (32, 128)
    fixed = rng.randint(0, cfg.vocab_size, (B, S))
    losses = []
    t0 = time.time()
    for step in range(steps):
        # smoke memorizes one batch so the loss-decrease assert is
        # meaningful; full mode streams fresh data
        ids = fixed.copy() if smoke else rng.randint(
            0, cfg.vocab_size, (B, S))
        labels = ids.copy()
        mask = rng.rand(B, S) < 0.15
        ids[mask] = 0                         # [MASK]
        xb = paddle.to_tensor(ids)
        yb = paddle.to_tensor(labels)
        with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
            logits = model(xb)
            loss = lossf(logits.reshape([-1, cfg.vocab_size]),
                         yb.reshape([-1]))
        opt.clear_grad()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        losses.append(float(loss.numpy()))
    dt = time.time() - t0
    print(f"bert_mlm_amp_o2: loss {losses[0]:.3f}->{losses[-1]:.3f} "
          f"({steps / dt:.2f} steps/s)")
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    return losses


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=10)
    a = ap.parse_args()
    main(a.smoke, a.steps)
