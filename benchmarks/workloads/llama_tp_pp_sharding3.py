"""Workload 4 (BASELINE.json configs): Llama-class hybrid parallel —
TP=4 x PP=2 (+ ZeRO param/state sharding where dp>1) on one mesh, via
the compiled hybrid engine (Megatron-SP sequence sharding on the tp
axis, collective-permute pipeline on the pp axis).

--smoke: tiny shapes, TP4xPP2 on the 8-device CPU mesh; full: 7B-class
dims on a pod slice.
"""
import sys
import os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import argparse
import time

import numpy as np


def main(smoke=True, steps=3):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    ndev = len(jax.devices())
    tp = 4 if ndev >= 8 else max(1, ndev // 2)
    pp = 2 if ndev >= 2 * tp else 1
    dp = max(1, ndev // (tp * pp))
    if smoke:
        cfg = GPTConfig(vocab_size=256, hidden_size=64,
                        num_layers=2 * max(pp, 1), num_heads=4,
                        max_seq_len=32)
        B, S, mb = 4, 32, 2
    else:
        # Llama-7B class dims
        cfg = GPTConfig(vocab_size=32000, hidden_size=4096,
                        num_layers=32, num_heads=32, max_seq_len=2048)
        B, S, mb = 2 * max(dp, 1), 2048, 4
    pcfg = ParallelConfig(dp=dp, pp=pp, tp=tp, sp=tp > 1,
                          microbatches=mb if pp > 1 else 1,
                          remat=not smoke, remat_policy="names",
                          zero1=True,
                          param_dtype=jnp.float32 if smoke
                          else jnp.bfloat16,
                          compute_dtype=jnp.float32 if smoke
                          else jnp.bfloat16)
    mesh, params, opt_state, step = setup(cfg, pcfg, seed=0)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)))
    losses = []
    t0 = time.time()
    with mesh:
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, (ids, ids))
            losses.append(float(loss))
    dt = time.time() - t0
    print(f"llama_tp{tp}_pp{pp}_dp{dp}: loss {losses[0]:.3f}->"
          f"{losses[-1]:.3f} ({B * S * steps / dt:,.0f} tok/s)")
    assert losses[-1] < losses[0]
    return losses


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=3)
    a = ap.parse_args()
    main(a.smoke, a.steps)
