"""Workload 5 (BASELINE.json configs): MoE with expert parallelism —
experts sharded over the dp axis, token dispatch = all-to-all over ICI
(reference: ERNIE-MoE / global_scatter-gather; here the EP einsum
dispatch in models/gpt_hybrid._moe_ffn lowers to XLA all-to-all).
"""
import sys
import os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import argparse
import time

import numpy as np


def main(smoke=True, steps=4):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    ndev = len(jax.devices())
    experts = 2 * ndev
    if smoke:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32)
        B, S = 8, 32
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024,
                        num_layers=24, num_heads=8, max_seq_len=1024)
        B, S = 4 * ndev, 1024
    pcfg = ParallelConfig(dp=ndev, pp=1, tp=1, num_experts=experts,
                          remat=not smoke, remat_policy="names",
                          zero1=True,
                          param_dtype=jnp.float32 if smoke
                          else jnp.bfloat16,
                          compute_dtype=jnp.float32 if smoke
                          else jnp.bfloat16)
    mesh, params, opt_state, step = setup(cfg, pcfg, seed=0)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)))
    losses = []
    t0 = time.time()
    with mesh:
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, (ids, ids))
            losses.append(float(loss))
    dt = time.time() - t0
    print(f"moe_ep{ndev}_e{experts}: loss {losses[0]:.3f}->"
          f"{losses[-1]:.3f} ({B * S * steps / dt:,.0f} tok/s)")
    assert losses[-1] < losses[0]
    return losses


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=4)
    a = ap.parse_args()
    main(a.smoke, a.steps)
