"""Workload 1 (BASELINE.json configs): ResNet-50 CIFAR-10 dygraph
training, single device (reference: paddle.vision + dygraph loop).

--smoke: tiny subset/model for CI; full mode trains resnet50 properly.
"""
import sys
import os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import argparse
import time

import numpy as np


def main(smoke=True, steps=20, use_jit=None):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.io import DataLoader
    from paddle_tpu.vision.datasets import Cifar10, FakeData
    from paddle_tpu.vision.models import resnet18, resnet50

    if use_jit is None:
        # full mode on TPU compiles the step (per-op eager dispatch
        # through the tunneled backend is latency-bound); smoke mode
        # exercises the eager engine
        use_jit = not smoke

    model = resnet18(num_classes=10) if smoke else resnet50(
        num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=5e-4)
    lossf = nn.CrossEntropyLoss()
    try:
        ds = Cifar10(mode="train")
    except FileNotFoundError:
        # zero-egress box without the archive cached: deterministic
        # synthetic CIFAR-shaped data (same item contract)
        ds = FakeData(size=256, image_shape=(3, 32, 32), num_classes=10)
    dl = DataLoader(ds, batch_size=8 if smoke else 256, shuffle=True)

    if smoke:
        # smoke overfits ONE batch (random labels are memorizable) so
        # the loss decrease is a meaningful assertion
        opt.set_lr(0.01)
    model.train()

    def train_step(xb, yb):
        loss = lossf(model(xb), yb)
        opt.clear_grad()
        loss.backward()
        opt.step()
        return loss

    step_fn = paddle.jit.to_static(train_step, objs=[model, opt]) \
        if use_jit else train_step
    losses = []
    t0 = time.time()
    it = iter(dl)
    fixed = next(it) if smoke else None
    for step in range(steps):
        if smoke:
            xb, yb = fixed
        else:
            try:
                xb, yb = next(it)
            except StopIteration:
                it = iter(dl)
                xb, yb = next(it)
        if xb.ndim == 2:                      # flat CIFAR rows
            xb = xb.reshape([xb.shape[0], 3, 32, 32])
        loss = step_fn(xb, yb)
        losses.append(float(loss.numpy()))
    dt = time.time() - t0
    print(f"resnet_cifar10: loss {losses[0]:.3f}->{losses[-1]:.3f} "
          f"({steps / dt:.2f} steps/s)")
    assert losses[-1] < losses[0], "not learning"
    return losses


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=20)
    a = ap.parse_args()
    main(a.smoke, a.steps)
