"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities (reference layer map in /root/repo/SURVEY.md §1).

Compute path: JAX/XLA (eager ops via cached per-primitive dispatch; whole
programs via paddle_tpu.jit); kernels: jnp/lax + Pallas for fused hot ops;
parallelism: jax.sharding SPMD over TPU meshes (paddle_tpu.distributed).
"""
from __future__ import annotations

# ---- core -----------------------------------------------------------------
from paddle_tpu.core.tensor import Tensor, Parameter  # noqa: F401
from paddle_tpu.core.dtype import (  # noqa: F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, finfo, iinfo, promote_types,
)
from paddle_tpu.core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, CustomPlace, IPUPlace, Place,
    TPUPlace, XPUPlace, get_device, set_device, is_compiled_with_tpu,
)
from paddle_tpu.core.generator import seed, default_generator  # noqa: F401
from paddle_tpu.core.flags import (  # noqa: F401
    get_flags, set_flags, define_flag,
)

# ---- ops (flat namespace like paddle.*) -----------------------------------
from paddle_tpu import ops  # noqa: F401  (patches Tensor methods)
from paddle_tpu.ops.creation import *  # noqa: F401,F403
from paddle_tpu.ops.math import *  # noqa: F401,F403
from paddle_tpu.ops.manipulation import *  # noqa: F401,F403
from paddle_tpu.ops.logic import *  # noqa: F401,F403
from paddle_tpu.ops.search import *  # noqa: F401,F403
from paddle_tpu.ops.linalg import (  # noqa: F401
    matmul, mm, bmm, mv, dot, cross, multi_dot, norm, dist, cdist, cholesky,
    cholesky_solve, inverse, solve, det, slogdet, t, einsum,
)
from paddle_tpu.ops.random import (  # noqa: F401
    rand, randn, randint, randint_like, randperm, uniform, normal,
    standard_normal, bernoulli, bernoulli_, binomial, multinomial, poisson,
    rand_like, randn_like, normal_, uniform_, exponential_,
)
from paddle_tpu.ops.extra import (  # noqa: F401
    renorm, reverse, shape, as_strided, reduce_as, gammaln, polygamma,
    gammainc, gammaincc, standard_gamma,
)
from paddle_tpu.ops.compat import (  # noqa: F401
    block_diag, cartesian_prod, combinations, vander, column_stack,
    row_stack, hsplit, vsplit, dsplit, unflatten, add_n, slice_scatter,
    select_scatter, diagonal_scatter, isin, histogram_bin_edges, pdist,
    sinc, sgn, signbit, frexp, ldexp, trapezoid, cumulative_trapezoid,
    multigammaln, log_normal, rank, tolist, is_complex, is_integer,
    is_floating_point, check_shape, disable_signal_handler,
    set_printoptions, get_rng_state, set_rng_state, get_cuda_rng_state,
    set_cuda_rng_state, create_parameter, batch, LazyGuard, flops,
    cauchy_, geometric_, log_normal_,
)

# ---- autograd -------------------------------------------------------------
from paddle_tpu import _C_ops  # noqa: F401  (generated dispatch surface)
from paddle_tpu import autograd  # noqa: F401
from paddle_tpu.autograd import (  # noqa: F401
    no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad,
)

# ---- subsystems -----------------------------------------------------------
from paddle_tpu import nn  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import amp  # noqa: F401
from paddle_tpu import io  # noqa: F401
from paddle_tpu import jit  # noqa: F401
from paddle_tpu.framework.io import save, load  # noqa: F401
from paddle_tpu.nn.layer.layers import ParamAttr  # noqa: F401

from paddle_tpu import distributed  # noqa: F401
from paddle_tpu import metric  # noqa: F401
import paddle_tpu.linalg as linalg  # noqa: F401

# heavier namespaces load lazily
_LAZY = {"vision", "hapi", "profiler", "static", "models", "parallel",
         "incubate", "distribution", "sparse", "device", "inference",
         "quantization", "utils", "text", "geometric", "audio",
         "regularizer", "sysconfig", "hub", "onnx", "tensor", "base",
         "callbacks", "dataset", "reader", "decomposition", "pir_utils",
         "batch", "observability", "training"}
import paddle_tpu.fft as fft  # noqa: F401
import paddle_tpu.signal as signal  # noqa: F401

# paddle.dtype is the dtype class itself (DataType in the reference);
# our dtypes are np.dtype instances (core/dtype.py).
import numpy as _np
dtype = _np.dtype

# generated `<op>_` inplace variants over every out-of-place op above
from paddle_tpu.ops.compat import _build_inplace_variants as _biv
globals().update(_biv(globals()))
del _biv


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f"paddle_tpu.{name}")
        if name == "batch":
            return mod.batch      # paddle.batch is the function itself
        return mod
    if name == "Model":
        from paddle_tpu.hapi import Model
        return Model
    if name == "DataParallel":
        from paddle_tpu.distributed.parallel import DataParallel
        return DataParallel
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY) +
                      ["Model", "DataParallel"]))


__version__ = "0.1.0"


def is_tensor(x):
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def get_flags_dict():
    return get_flags()


def device_count():
    import jax
    return len(jax.devices())


def synchronize():
    """Block until all queued device work completes (paddle.device.cuda
    .synchronize equivalent — XLA: block_until_ready on a trivial op)."""
    import jax
    (jax.device_put(0) + 0).block_until_ready()


def in_dynamic_mode():
    return True


def disable_static(place=None):
    pass


def enable_static():
    raise NotImplementedError(
        "legacy static program mode is replaced by paddle_tpu.jit.to_static "
        "(XLA program capture); see paddle_tpu.static")


def summary(net, input_size=None, dtypes=None, input=None):
    total = 0
    trainable = 0
    for _, p in net.named_parameters():
        n = p.size
        total += n
        if not p.stop_gradient:
            trainable += n
    return {"total_params": total, "trainable_params": trainable}


# bind the rest of the reference Tensor-method surface: every method in
# the reference tensor_method_func list whose op exists at module level
# becomes a Tensor method (the reference's monkey-patch pass,
# python/paddle/tensor/__init__.py)
from paddle_tpu.ops.tensor_methods import patch_from_modules as _pfm
_pfm()
del _pfm
