"""Fault-injection hook points for the serving AND training
robustness suites.

The serving stack calls :func:`hit` at NAMED SITES (e.g.
``serving.decode_step``); the training stack (ISSUE 15) adds
``train.step`` (hapi ``Model.train_batch`` + fleet
``PipelineParallel.train_batch``, ctx ``step=``), ``train.data_fetch``
(the ``fit`` loop's batch fetch), ``train.checkpoint_save``
(``distributed.checkpoint.save_state_dict``'s write path, AFTER the
stale commit marker is dropped — a fault there models a writer killed
mid-save), and ``train.preempt`` (``FaultTolerantCheckpoint``'s step
boundary — an injected error is treated as a delivered preemption
notice, driving the flush-and-stop path without a real SIGTERM).
When the ``PADDLE_TPU_CHAOS`` env var is
unset — the production default — ``hit`` is a single dict/env check
and nothing else ever runs; no rule matching, no allocation. With the
env var set, installed rules can inject

  * ``error``  — raise :class:`ChaosError` (a step exception),
  * ``alloc``  — raise :class:`ChaosAllocError` (an allocation
    failure, message shaped like XLA's RESOURCE_EXHAUSTED),
  * ``slow``   — sleep ``seconds`` (a slow step), then continue,

either a bounded number of ``times`` (transient fault) or forever
(persistent fault). Rules may carry a ``match(ctx)`` predicate over
the site's context kwargs — e.g. fail the decode step only while a
poison request's slot is in the active set — which is what lets the
recovery tests prove bisection finds the *request*, not just the step.

Two ways to install rules:

  * programmatic (tests): ``install("serving.decode_step",
    kind="error", times=2)`` / ``clear()`` — requires
    ``PADDLE_TPU_CHAOS`` to be set (any non-empty value, e.g. ``on``)
    so a stray import can never inject faults into production;
  * env spec (no code): ``PADDLE_TPU_CHAOS=
    "serving.decode_step:error:3;serving.drain:slow:0.2"`` — each
    clause is ``site:kind[:arg]`` where ``arg`` is ``times`` for
    error/alloc and ``seconds`` for slow.

Reference posture: fault injection as a first-class serving test tool
(the Orca/vLLM lineage pairs continuous batching with failure drills);
training-side fault tests (tests/test_elastic_fault.py) kill real
processes, serving tests inject at these hooks instead because one
poison request must NOT kill the process.
"""
from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

ENV = "PADDLE_TPU_CHAOS"

KINDS = ("error", "slow", "alloc")


class ChaosError(RuntimeError):
    """Injected step exception."""


class ChaosAllocError(ChaosError):
    """Injected allocation failure."""


class Rule:
    """One injection rule; ``times=None`` means persistent."""

    __slots__ = ("site", "kind", "times", "seconds", "match", "fired",
                 "from_env")

    def __init__(self, site: str, kind: str = "error",
                 times: Optional[int] = None, seconds: float = 0.05,
                 match: Optional[Callable[[dict], bool]] = None,
                 from_env: bool = False):
        if kind not in KINDS:
            raise ValueError(f"chaos kind {kind!r} not in {KINDS}")
        self.site = site
        self.kind = kind
        self.times = times
        self.seconds = float(seconds)
        self.match = match
        self.fired = 0
        #: parsed from the env spec (replaced wholesale on re-parse)
        self.from_env = from_env

    def _applies(self, site: str, ctx: dict) -> bool:
        if site != self.site:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.match is not None and not self.match(ctx):
            return False
        return True

    def _fire(self, site: str):
        self.fired += 1
        if self.kind == "slow":
            time.sleep(self.seconds)
            return
        if self.kind == "alloc":
            raise ChaosAllocError(
                f"RESOURCE_EXHAUSTED: chaos allocation failure injected "
                f"at {site} (fire #{self.fired})")
        raise ChaosError(
            f"chaos error injected at {site} (fire #{self.fired})")


_rules: List[Rule] = []
#: env spec string already parsed into _rules (parse once per value)
_parsed_env: Optional[str] = None


def active() -> bool:
    """Chaos is armed only while the env var is non-empty."""
    return bool(os.environ.get(ENV, "").strip())


def install(site: str, kind: str = "error", times: Optional[int] = None,
            seconds: float = 0.05,
            match: Optional[Callable[[dict], bool]] = None) -> Rule:
    """Install one programmatic rule (tests). The rule only ever fires
    while ``PADDLE_TPU_CHAOS`` is set."""
    rule = Rule(site, kind, times, seconds, match)
    _rules.append(rule)
    return rule


def clear() -> None:
    """Drop every installed rule and forget the parsed env spec."""
    global _parsed_env
    _rules.clear()
    _parsed_env = None


def _parse_env(spec: str) -> None:
    """Parse ``site:kind[:arg]`` clauses; bare enable values ("on",
    "1") install nothing. Malformed clauses are skipped — chaos config
    must never crash the serving process it is trying to harden. A
    CHANGED spec replaces the previous spec's rules wholesale
    (programmatic rules are untouched) — an operator switching
    experiments must not keep the old faults firing."""
    global _parsed_env
    _parsed_env = spec
    _rules[:] = [r for r in _rules if not r.from_env]
    for clause in spec.split(";"):
        parts = clause.strip().split(":")
        if len(parts) < 2 or parts[1] not in KINDS:
            continue
        site, kind = parts[0], parts[1]
        try:
            arg = float(parts[2]) if len(parts) > 2 else None
        except ValueError:
            continue
        if kind == "slow":
            _rules.append(Rule(site, kind, seconds=arg or 0.05,
                               from_env=True))
        else:
            _rules.append(Rule(
                site, kind, times=int(arg) if arg is not None else None,
                from_env=True))


def hit(site: str, **ctx) -> None:
    """Chaos hook point: no-op unless ``PADDLE_TPU_CHAOS`` is set AND
    a matching rule has budget left. Call sites pass whatever context
    a predicate might key on (``slots=...``, ``rid=...``)."""
    global _parsed_env
    spec = os.environ.get(ENV, "").strip()
    if not spec:
        return
    if spec != _parsed_env:
        if spec.lower() in ("1", "on", "true"):
            # bare arming value: drop any previous env-spec rules,
            # keep programmatic ones
            _parsed_env = spec
            _rules[:] = [r for r in _rules if not r.from_env]
        else:
            _parse_env(spec)
    for rule in _rules:
        if rule._applies(site, ctx):
            rule._fire(site)
