"""Test/bench environment helpers.

This box's axon sitecustomize registers a tunneled-TPU PJRT backend whose
client creation can block when the tunnel is unhealthy; CPU-only runs
(tests, bench smoke, subprocess workers) must neutralize it BEFORE the
first jax operation. This is the single home for that private-API
surgery — conftest.py, bench.py, and spawned worker scripts all import
it so a jax upgrade only needs one fix.
"""
from __future__ import annotations

import os


def unshim_axon(pop_tpu: bool = False) -> None:
    """Remove the axon backend factory and restore jax's original
    backend lookup. Call after `import jax` but before the first op.

    pop_tpu: also unregister the tpu factory (bench CPU smoke). Tests
    keep it registered — JAX_PLATFORMS=cpu already prevents creation,
    and unregistering would break importing pallas kernels.
    """
    import jax._src.xla_bridge as xb

    xb._backend_factories.pop("axon", None)
    if pop_tpu:
        xb._backend_factories.pop("tpu", None)
    f = xb._get_backend_uncached
    if getattr(f, "__name__", "") == "_axon_get_backend_uncached" \
            and f.__closure__:
        xb._get_backend_uncached = f.__closure__[0].cell_contents


def force_cpu(num_devices: int | None = None,
              pop_tpu: bool = False) -> None:
    """Full CPU-backend setup for a fresh process: env + jax config +
    unshim. Must run before the first jax operation; num_devices > 1
    adds the virtual-device XLA flag (only effective if jax hasn't
    created a backend yet)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if num_devices and num_devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={num_devices}"
            ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    unshim_axon(pop_tpu=pop_tpu)
