"""AMP (reference: python/paddle/amp — auto_cast :1014, decorate :1099,
GradScaler grad_scaler.py:645, op lists amp_lists.py:33).

TPU-native defaults: bf16 first (no loss scaling needed), fp16 supported for
parity. The auto-cast hook plugs into core.dispatch exactly where the
generated ad_funcs apply AMP_LOGIC (eager_gen.py:588).
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core import dispatch as _dispatch
from paddle_tpu.core import dtype as dtype_mod
from paddle_tpu.core.tensor import Tensor

# ---- op lists (reference amp_lists.py / imperative/amp_auto_cast.h) -------
WHITE_LIST = {
    "matmul", "linear", "bmm", "mm", "mv", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "addmm", "scaled_dot_product_attention", "flash_attn_unpadded",
}
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "std",
    "var", "cos_sim", "softmax", "log_softmax", "cross_entropy",
    "softmax_with_cross_entropy", "sigmoid_focal_loss", "bce", "bce_logits",
    "layer_norm", "rms_norm", "batch_norm", "batch_norm_infer", "norm",
    "cumsum", "logsumexp", "erfinv", "pow", "logcumsumexp", "kl_div",
    "l1_loss", "mse_loss", "nll_loss", "smooth_l1_loss", "huber_loss",
    "linspace", "prod", "acos", "asin", "cosh", "sinh", "tan", "atanh",
    "acosh", "asinh",
}


class _AmpState:
    def __init__(self):
        self.enabled = False
        self.level = "O0"
        self.dtype = dtype_mod.bfloat16
        self.custom_white = set()
        self.custom_black = set()


_STATE = _AmpState()


_LOW_PRECISION_OPS = {}


def _record_low_precision(name, dt):
    from paddle_tpu.core.flags import get_flag
    if get_flag("FLAGS_low_precision_op_list"):
        key = f"{name}->{np.dtype(dt).name}"
        _LOW_PRECISION_OPS[key] = _LOW_PRECISION_OPS.get(key, 0) + 1


def _amp_hook(name, arrays):
    st = _STATE
    if not st.enabled or st.level == "O0":
        return arrays
    white = (WHITE_LIST | st.custom_white) - st.custom_black
    black = (BLACK_LIST | st.custom_black) - st.custom_white
    target = jnp.bfloat16 if st.dtype == dtype_mod.bfloat16 else jnp.float16

    def cast_to(arrs, dt):
        return [a.astype(dt)
                if jnp.issubdtype(a.dtype, jnp.floating)
                and a.dtype != jnp.float64 and a.dtype != dt else a
                for a in arrs]

    if name in white:
        _record_low_precision(name, target)
        return cast_to(arrays, target)
    if name in black:
        return cast_to(arrays, jnp.float32)
    if st.level == "O2" and name not in black:
        _record_low_precision(name, target)
        return cast_to(arrays, target)
    # O1 gray list: promote to widest float among inputs
    f_dtypes = [a.dtype for a in arrays
                if jnp.issubdtype(a.dtype, jnp.floating)]
    if len(set(f_dtypes)) > 1:
        widest = jnp.float32 if jnp.float32 in f_dtypes else target
        return cast_to(arrays, widest)
    return arrays


_dispatch.set_amp_hook(_amp_hook)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast (amp/auto_cast.py:1014)."""
    st = _STATE
    prev = (st.enabled, st.level, st.dtype, st.custom_white, st.custom_black)
    st.enabled = enable
    st.level = level if enable else "O0"
    st.dtype = dtype_mod.convert_dtype(dtype)
    st.custom_white = set(custom_white_list or ())
    st.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (st.enabled, st.level, st.dtype, st.custom_white,
         st.custom_black) = prev


amp_guard = auto_cast


def is_auto_cast_enabled():
    return _STATE.enabled


def get_amp_dtype():
    return "bfloat16" if _STATE.dtype == dtype_mod.bfloat16 else "float16"


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False):
    """paddle.amp.decorate (auto_cast.py:1099): O2 casts the model params
    to the AMP dtype; optimizer gets fp32 master weights."""
    d = dtype_mod.convert_dtype(dtype)
    model_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        for m in model_list:
            m.astype(d)
        if optimizers is not None:
            opt_list = optimizers if isinstance(optimizers, (list, tuple)) \
                else [optimizers]
            for opt in opt_list:
                opt._multi_precision = True if master_weight is None \
                    else master_weight
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Loss scaling (reference amp/grad_scaler.py:645 + the device-side
    check_finite_and_unscale / update_loss_scaling kernels,
    phi/kernels/amp_kernel.h:25). With bf16 scaling is a no-op by default
    (enable=False mirrors reference behavior for bf16)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = Tensor._wrap(jnp.asarray(init_loss_scaling,
                                               jnp.float32))
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        self._last_skipped = False

    def is_enable(self):
        return self._enable

    @property
    def found_inf(self):
        return self._found_inf

    def last_step_skipped(self):
        """True when the most recent ``step()`` skipped the optimizer
        update because check_finite_and_unscale found non-finite
        grads — the hook ``training.StepGuard.observe_scaler`` uses
        so AMP's own skip-step semantics feed the circuit breaker
        instead of being double-counted as NaN steps."""
        return self._enable and self._last_skipped

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor._wrap(self._scale._data)

    def set_init_loss_scaling(self, v):
        self._scale._assign_array(jnp.asarray(v, jnp.float32))

    def scale(self, var):
        if not self._enable:
            return var
        from paddle_tpu.core.dispatch import run_op
        s = self._scale
        return run_op("scale_loss",
                      lambda a, sc: a * sc.astype(a.dtype), var, s)

    def _unscale(self, optimizer):
        """check_finite_and_unscale (amp_kernel.h:25) over all grads."""
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale._data
        found = jnp.zeros((), jnp.bool_)
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g32 = p.grad._data.astype(jnp.float32) * inv
            found = found | ~jnp.isfinite(g32).all()
            p.grad._assign_array(g32.astype(p.grad._data.dtype))
        self._found_inf = bool(found)
        self._unscaled = True

    def unscale_(self, optimizer):
        return self._unscale(optimizer)

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self._unscale(optimizer)
        self._last_skipped = self._found_inf
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        """update_loss_scaling (amp_kernel.h:32)."""
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale._assign_array(
                    jnp.maximum(self._scale._data * self._decr_ratio, 1.0))
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale._assign_array(
                    self._scale._data * self._incr_ratio)
                self._good_steps = 0

    def state_dict(self):
        return {
            "scale": np.asarray(self._scale._data),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
        }

    def load_state_dict(self, sd):
        self._scale._assign_array(jnp.asarray(sd["scale"]))
        self._good_steps = sd.get("incr_count", 0)
        self._bad_steps = sd.get("decr_count", 0)


AmpScaler = GradScaler


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return jax.default_backend() != "cpu"
