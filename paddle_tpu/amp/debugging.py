"""paddle.amp.debugging equivalent (reference:
python/paddle/amp/debugging.py — per-op NaN/Inf checking config +
operator stats collection over the C++ NaN scanner / op counters).

Hooks into the eager dispatcher (core/dispatch.py run_op): the NaN scan
is the FLAGS_check_nan_inf path; op stats count per-op dtype calls."""
from __future__ import annotations

import contextlib
from collections import Counter
from enum import Enum
from typing import Optional

import jax.numpy as jnp

from paddle_tpu.core import dispatch as _dispatch
from paddle_tpu.core.flags import get_flags, set_flags


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    CHECK_ALL_ABORT = 4
    CHECK_ALL_PRINT = 5
    DUMP_ALL = 6


class TensorCheckerConfig:
    """reference debugging.py TensorCheckerConfig."""

    def __init__(self, enable=False,
                 debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None,
                 stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list or []
        self.skipped_op_list = skipped_op_list or []
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit


_checker_config: Optional[TensorCheckerConfig] = None


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """Turn on per-op NaN/Inf checking (reference
    enable_tensor_checker)."""
    global _checker_config
    _checker_config = checker_config
    if checker_config.enable:
        set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    global _checker_config
    _checker_config = None
    set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Scan one tensor for NaN/Inf (reference check_numerics)."""
    import numpy as np
    a = tensor._data if hasattr(tensor, "_data") else jnp.asarray(tensor)
    stats = (jnp.isnan(a).sum(), jnp.isinf(a).sum())
    n_nan, n_inf = int(stats[0]), int(stats[1])
    if n_nan or n_inf:
        msg = (f"[check_numerics] op={op_type} var={var_name}: "
               f"{n_nan} NaN, {n_inf} Inf")
        if debug_mode in (None, DebugMode.CHECK_NAN_INF_AND_ABORT):
            raise RuntimeError(msg)
        print(msg)
    return n_nan, n_inf


# ------------------------------------------------------ operator stats
_op_stats: Optional[Counter] = None
_remove_observer = None


def _stats_observer(name, arrays):
    if _op_stats is not None:
        dtypes = {str(a.dtype) for a in arrays
                  if hasattr(a, "dtype")} or {"-"}
        for dt in dtypes:
            _op_stats[f"{name}:{dt}"] += 1


def enable_operator_stats_collection():
    """Start counting per-op/dtype calls (reference
    enable_operator_stats_collection)."""
    global _op_stats, _remove_observer
    _op_stats = Counter()
    _remove_observer = _dispatch.add_op_observer(_stats_observer)


def disable_operator_stats_collection():
    """Stop and print the collected table."""
    global _op_stats, _remove_observer
    if _remove_observer is not None:
        _remove_observer()
        _remove_observer = None
    if _op_stats:
        print("<------------------------------ op list ------------------"
              "------------>")
        for key, count in sorted(_op_stats.items()):
            print(f"  {key}  called {count} times")
        print("<----------------------------------- op list -------------"
              "---------------->")
    _op_stats = None


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    raise NotImplementedError(
        "compare_accuracy consumes GPU dump files; on TPU compare runs "
        "with paddle_tpu.utils.run_check-style numpy oracles instead")


def get_low_precision_op_list():
    """Ops auto-cast to low precision by AMP since
    FLAGS_low_precision_op_list was enabled (reference
    amp/debugging.py low-precision op collection): {"op->dtype": count}.
    """
    from paddle_tpu.amp import _LOW_PRECISION_OPS
    return dict(_LOW_PRECISION_OPS)


def clear_low_precision_op_list():
    from paddle_tpu.amp import _LOW_PRECISION_OPS
    _LOW_PRECISION_OPS.clear()
