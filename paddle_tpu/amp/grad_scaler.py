"""paddle.amp.grad_scaler module path (reference:
python/paddle/amp/grad_scaler.py)."""
from . import GradScaler, AmpScaler  # noqa: F401
from enum import Enum


class OptimizerState(Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2
