"""paddle.audio equivalent (reference: python/paddle/audio): mel/window
DSP functional, feature layers, wav IO."""
from __future__ import annotations

from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from . import features  # noqa: F401
from . import functional  # noqa: F401
from .backends import info, load, save  # noqa: F401

__all__ = ["functional", "features", "datasets", "backends", "load",
           "info", "save"]
