"""paddle.audio.backends (reference: python/paddle/audio/backends):
wave-module wav IO (the reference's soundfile backend is optional there
too)."""
from __future__ import annotations

import wave as _wave
from dataclasses import dataclass

import numpy as np

from paddle_tpu.core.tensor import Tensor


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return "wave"


def set_backend(backend_name):
    if backend_name != "wave":
        raise ValueError("only the built-in 'wave' backend is available")


def info(filepath, format=None):
    with _wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(), w.getnchannels(),
                         w.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True, format=None):
    with _wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(frame_offset)
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(count)
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dt).reshape(-1, ch)
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16, format=None):
    arr = np.asarray(src.numpy() if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T
    if arr.dtype in (np.float32, np.float64):
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * (2 ** (bits_per_sample - 1) - 1)).astype(
            {8: np.uint8, 16: np.int16, 32: np.int32}[bits_per_sample])
    with _wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        w.setsampwidth(bits_per_sample // 8)
        w.setframerate(sample_rate)
        w.writeframes(arr.tobytes())


__all__ = ["info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]
