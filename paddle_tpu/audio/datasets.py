"""paddle.audio.datasets (reference: python/paddle/audio/datasets):
TESS / ESC50 over pre-placed files (no network egress here)."""
from __future__ import annotations

import os

import numpy as np

from paddle_tpu.io import Dataset


class _AudioFolderDataset(Dataset):
    _NAME = ""

    def __init__(self, mode="train", feat_type="raw", data_dir=None,
                 archive=None, **kw):
        root = data_dir or os.path.expanduser(
            f"~/.cache/paddle_tpu/{self._NAME}")
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"{type(self).__name__} data not found at {root} "
                "(no network access; place extracted wavs there)")
        self.files = []
        self.labels = []
        for dirpath, _, files in os.walk(root):
            for f in sorted(files):
                if f.endswith(".wav"):
                    self.files.append(os.path.join(dirpath, f))
                    self.labels.append(os.path.basename(dirpath))
        names = sorted(set(self.labels))
        self.label_ids = {n: i for i, n in enumerate(names)}
        self.feat_type = feat_type

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        from .backends import load
        wav, sr = load(self.files[idx])
        return wav, np.int64(self.label_ids[self.labels[idx]])


class TESS(_AudioFolderDataset):
    _NAME = "tess"
    n_class = 7


class ESC50(_AudioFolderDataset):
    _NAME = "esc50"
    n_class = 50


__all__ = ["TESS", "ESC50"]
