"""paddle.audio.functional (reference:
python/paddle/audio/functional/{functional,window}.py): mel scale math,
DCT matrix, windows — all static host math producing device tensors."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


def hz_to_mel(freq, htk=False):
    scalar = isinstance(freq, (int, float))
    f = np.asarray(freq, np.float64) if not isinstance(freq, Tensor) \
        else np.asarray(freq.numpy())
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, mel)
    if scalar:
        return float(mel)
    return Tensor(mel.astype(np.float32)) if isinstance(freq, Tensor) \
        else mel


def mel_to_hz(mel, htk=False):
    scalar = isinstance(mel, (int, float))
    m = np.asarray(mel, np.float64) if not isinstance(mel, Tensor) \
        else np.asarray(mel.numpy())
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)), hz)
    if scalar:
        return float(hz)
    return Tensor(hz.astype(np.float32)) if isinstance(mel, Tensor) else hz


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                       n_mels)
    return Tensor(np.asarray(mel_to_hz(mels, htk), dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(np.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1+n_fft//2] (reference
    compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fftfreqs = np.linspace(0, sr / 2, 1 + n_fft // 2)
    melpts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                         n_mels + 2)
    hzpts = np.asarray(mel_to_hz(melpts, htk))
    fdiff = np.diff(hzpts)
    ramps = hzpts[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (hzpts[2:n_mels + 2] - hzpts[:n_mels])
        weights *= enorm[:, None]
    return Tensor(weights.astype(dtype))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(np.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / np.sqrt(2)
        dct *= np.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(dct.T.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    from paddle_tpu.core.dispatch import run_op
    x = spect if isinstance(spect, Tensor) else Tensor(np.asarray(spect))

    def f(a):
        log_spec = 10.0 * jnp.log10(jnp.maximum(a, amin))
        log_spec = log_spec - 10.0 * np.log10(max(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    return run_op("power_to_db", f, x)


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window function table (reference functional/window.py)."""
    if isinstance(window, tuple):
        name, *params = window
    else:
        name, params = window, []
    n = win_length
    sym = not fftbins
    denom = n - 1 if sym else n
    t = np.arange(n)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * t / denom)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * t / denom)
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * t / denom)
             + 0.08 * np.cos(4 * np.pi * t / denom))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * t / denom - 1.0)
    elif name == "bohman":
        x = np.abs(2 * t / denom - 1.0)
        w = (1 - x) * np.cos(np.pi * x) + np.sin(np.pi * x) / np.pi
    elif name == "nuttall":
        a = [0.3635819, 0.4891775, 0.1365995, 0.0106411]
        w = (a[0] - a[1] * np.cos(2 * np.pi * t / denom)
             + a[2] * np.cos(4 * np.pi * t / denom)
             - a[3] * np.cos(6 * np.pi * t / denom))
    elif name == "gaussian":
        std = params[0] if params else 1.0
        w = np.exp(-0.5 * ((t - (n - 1) / 2) / (std * (n - 1) / 2)) ** 2) \
            if sym else np.exp(-0.5 * ((t - n / 2) / (std * n / 2)) ** 2)
    elif name == "general_gaussian":
        p, sig = (params + [1.0, 1.0])[:2]
        w = np.exp(-0.5 * np.abs((t - (n - 1) / 2) / sig) ** (2 * p))
    elif name == "exponential":
        tau = params[0] if params else 1.0
        w = np.exp(-np.abs(t - (n - 1) / 2) / tau)
    elif name == "triang":
        w = 1.0 - np.abs((t - (n - 1) / 2) / ((n + 1) / 2 if not sym
                                              else (n - 1) / 2 + 0.5))
    elif name in ("boxcar", "rectangular", "ones"):
        w = np.ones(n)
    elif name == "cosine":
        w = np.sin(np.pi * (t + 0.5) / n)
    elif name == "kaiser":
        beta = params[0] if params else 12.0
        w = np.kaiser(n, beta)
    elif name == "taylor":
        # 4-term Taylor window, -30 dB sidelobes (scipy default)
        nbar, sll = 4, 30
        b = 10 ** (sll / 20)
        a = np.arccosh(b) / np.pi
        s2 = nbar ** 2 / (a ** 2 + (nbar - 0.5) ** 2)
        fm = np.zeros(nbar - 1)
        signs = (-1) ** np.arange(1, nbar)
        m2 = np.arange(1, nbar) ** 2
        for mi in range(1, nbar):
            num = np.prod(1 - m2[mi - 1] / s2
                          / (a ** 2 + (np.arange(nbar - 1) + 0.5) ** 2))
            den = np.prod(1 - m2[mi - 1] / m2[np.arange(nbar - 1)
                                              != mi - 1])
            fm[mi - 1] = signs[mi - 1] * num / (2 * den)
        w = np.ones(n)
        for mi in range(1, nbar):
            w = w + 2 * fm[mi - 1] * np.cos(
                2 * np.pi * mi * (t - (n - 1) / 2) / n)
    else:
        raise ValueError(f"unknown window {name!r}")
    return Tensor(w.astype(dtype))


__all__ = ["compute_fbank_matrix", "create_dct", "fft_frequencies",
           "hz_to_mel", "mel_frequencies", "mel_to_hz", "power_to_db",
           "get_window"]
