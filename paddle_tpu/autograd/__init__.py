"""paddle.autograd equivalent: grad-mode guards, paddle.grad (GeneralGrad,
eager/general_grad.h), PyLayer (eager/pylayer), functional jacobian/hessian.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core import dispatch as _dispatch
from paddle_tpu.core.tensor import Tensor
from .tape import Edge, GradNode, run_backward

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "is_grad_enabled", "PyLayer", "PyLayerContext", "jacobian", "hessian",
    "saved_tensors_hooks",
]


class _GradGuard:
    """Context manager + decorator (paddle.no_grad / enable_grad)."""

    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = _dispatch.set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        _dispatch.set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        if not callable(fn):
            raise TypeError("no_grad used as decorator needs a callable")
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with self.__class__():
                return fn(*a, **k)
        return wrapper


class no_grad(_GradGuard):
    def __init__(self):
        super().__init__(False)


class enable_grad(_GradGuard):
    def __init__(self):
        super().__init__(True)


class set_grad_enabled(_GradGuard):
    def __init__(self, mode: bool):
        super().__init__(mode)


def is_grad_enabled() -> bool:
    return _dispatch.grad_enabled()


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad: partial-graph gradient (reference GeneralGrad,
    eager/general_grad.h) — returns grads without mutating .grad."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    captured = run_backward(list(outputs), grad_outputs,
                            retain_graph=retain_graph, targets=list(inputs),
                            accumulate_leaf=False)
    result = []
    for t in inputs:
        g = captured.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient; pass "
                    "allow_unused=True to get None instead")
            result.append(None)
        else:
            result.append(Tensor._wrap(g, stop_gradient=not create_graph))
    return result


# --------------------------------------------------------------------------
# PyLayer: user-defined autograd function (reference eager/pylayer +
# fluid/pybind/eager_py_layer.cc)
# --------------------------------------------------------------------------
#: active (pack, unpack) hook pairs for tensors saved for backward
#: (reference autograd/saved_tensors_hooks — TensorWrapper pack/unpack
#: hooks; here they intercept PyLayer save_for_backward captures)
_saved_tensors_hooks = []


class saved_tensors_hooks:
    """Context manager: pack_hook(tensor) runs when a tensor is saved
    for backward, unpack_hook(packed) when it is retrieved — the
    CPU-offload / recompute seam (reference
    python/paddle/autograd/saved_tensors_hooks.py)."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _saved_tensors_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_tensors_hooks.pop()
        return False


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._unpack = None
        self.materialize_grads = True
        self._non_differentiable = ()

    def save_for_backward(self, *tensors):
        if _saved_tensors_hooks:
            pack, unpack = _saved_tensors_hooks[-1]
            self._saved = tuple(pack(t) for t in tensors)
            self._unpack = unpack
        else:
            self._saved = tensors

    def _unpacked(self):
        if self._unpack is not None:
            return tuple(self._unpack(p) for p in self._saved)
        return self._saved

    @property
    def saved_tensor(self):
        return self._unpacked()

    def saved_tensors(self):
        return self._unpacked()

    def mark_non_differentiable(self, *tensors):
        self._non_differentiable = tensors

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


class PyLayer:
    """Subclass with static forward(ctx, *args) / backward(ctx, *grads)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        record = _dispatch.grad_enabled() and any(
            not t.stop_gradient for t in in_tensors)
        if record:
            diff_inputs = [t for t in in_tensors
                           if jnp.issubdtype(t._data.dtype, jnp.inexact)]
            nondiff_out_ids = {id(t) for t in ctx._non_differentiable}
            out_t = [t for t in out_list if isinstance(t, Tensor)]

            def vjp_fn(cotangents):
                cts = [Tensor._wrap(c, True) if not isinstance(c, Tensor)
                       else c for c in cotangents]
                with no_grad():
                    gin = cls.backward(ctx, *cts)
                gin = [gin] if isinstance(gin, Tensor) or gin is None \
                    else list(gin)
                grads = []
                gi = iter(gin)
                for t in diff_inputs:
                    g = next(gi, None)
                    grads.append(jnp.zeros(t.shape, t.dtype) if g is None
                                 else (g._data if isinstance(g, Tensor) else g))
                return tuple(grads)

            edges = []
            for t in diff_inputs:
                if t.stop_gradient:
                    edges.append(None)
                elif t._grad_node is not None:
                    edges.append(Edge(node=t._grad_node, out_idx=t._out_idx))
                else:
                    edges.append(Edge(leaf=t))
            avals = [(tuple(t.shape), t._data.dtype) for t in out_t]
            node = GradNode(cls.__name__, vjp_fn, edges, avals)
            import weakref
            for i, t in enumerate(out_t):
                if id(t) not in nondiff_out_ids:
                    t.stop_gradient = False
                    t._grad_node = node
                    t._out_idx = i
                    node.out_refs[i] = weakref.ref(t)
        return out_list[0] if single else tuple(out_list)


# --------------------------------------------------------------------------
# Functional higher-order API (paddle.autograd.jacobian / hessian) — here we
# delegate straight to jax's transforms over a wrapped pure function.
# --------------------------------------------------------------------------
def _as_pure(func):
    def pure(*arrays):
        ts = [Tensor._wrap(a, stop_gradient=False) for a in arrays]
        out = func(*ts)
        return out._data if isinstance(out, Tensor) else out
    return pure


def jacobian(func, xs, create_graph=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_l]
    jac = jax.jacrev(_as_pure(func), argnums=tuple(range(len(arrays))))(*arrays)
    outs = [Tensor._wrap(j, True) for j in jac]
    return outs[0] if not isinstance(xs, (list, tuple)) else outs


def hessian(func, xs, create_graph=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_l]
    hes = jax.hessian(_as_pure(func), argnums=tuple(range(len(arrays))))(*arrays)
    if not isinstance(xs, (list, tuple)):
        h = hes[0][0] if isinstance(hes, (tuple, list)) else hes
        return Tensor._wrap(h, True)
    return hes
