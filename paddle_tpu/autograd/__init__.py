"""paddle.autograd equivalent: grad-mode guards, paddle.grad (GeneralGrad,
eager/general_grad.h), PyLayer (eager/pylayer), functional jacobian/hessian.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core import dispatch as _dispatch
from paddle_tpu.core.tensor import Tensor
from .tape import Edge, GradNode, run_backward

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "is_grad_enabled", "PyLayer", "PyLayerContext", "jacobian", "hessian",
    "saved_tensors_hooks",
]


class _GradGuard:
    """Context manager + decorator (paddle.no_grad / enable_grad)."""

    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = _dispatch.set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        _dispatch.set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        if not callable(fn):
            raise TypeError("no_grad used as decorator needs a callable")
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with self.__class__():
                return fn(*a, **k)
        return wrapper


class no_grad(_GradGuard):
    def __init__(self):
        super().__init__(False)


class enable_grad(_GradGuard):
    def __init__(self):
        super().__init__(True)


class set_grad_enabled(_GradGuard):
    def __init__(self, mode: bool):
        super().__init__(mode)


def is_grad_enabled() -> bool:
    return _dispatch.grad_enabled()


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad: partial-graph gradient (reference GeneralGrad,
    eager/general_grad.h) — returns grads without mutating .grad.

    With create_graph=True the gradient computation itself is recorded
    on the tape, so repeated grad() calls give true higher-order eager
    derivatives — the capability the reference implements with its 105
    hand-written *_double_grad ops (phi/ops/yaml/backward.yaml:4). The
    TPU-native mechanism: the recorded subgraph from `outputs` down to
    `inputs` is replayed as a pure jax function and its vjp is executed
    as ONE new tape op, whose own jax.vjp supplies the next order.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    if create_graph:
        return _grad_create_graph(list(outputs), list(inputs),
                                  grad_outputs, allow_unused)
    captured = run_backward(list(outputs), grad_outputs,
                            retain_graph=retain_graph, targets=list(inputs),
                            accumulate_leaf=False)
    result = []
    for t in inputs:
        g = captured.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient; pass "
                    "allow_unused=True to get None instead")
            result.append(None)
        else:
            # create_graph=True returned earlier via _grad_create_graph
            result.append(Tensor._wrap(g, stop_gradient=True))
    return result


# --------------------------------------------------------------------------
# Higher-order eager grad: functional replay of the recorded subgraph
# --------------------------------------------------------------------------
def _replay_plan(outputs, inputs):
    """Build the replay of the tape subgraph from `outputs` cut at
    `inputs`.

    Every differentiable source the subgraph touches becomes a slot:
    the requested `inputs` (cut points) first, then every
    differentiable leaf discovered while walking — so the recorded
    gradient op stays connected to ALL upstream parameters (a second
    backward must reach e.g. the discriminator weights in a gradient
    penalty, not just the requested x).

    Returns (F, slot_of, reps, used_slots): F maps one array per slot
    to the tuple of output arrays; slot_of[i] is the slot of inputs[i]
    (duplicates share one); reps is one representative Tensor per slot
    (tape linkage for the composite op); used_slots are the requested
    slots the outputs actually depend on through differentiable edges.
    """
    leaf_slot = {}       # id(leaf tensor) -> slot
    nodeslot_slot = {}   # (id(node), out_idx) -> slot
    slot_of = []
    reps = []
    for t in inputs:
        key = ((id(t._grad_node), t._out_idx) if t._grad_node is not None
               else id(t))
        table = nodeslot_slot if t._grad_node is not None else leaf_slot
        if key in table:
            slot_of.append(table[key])
        else:
            table[key] = len(reps)
            slot_of.append(len(reps))
            reps.append(t)

    def _not_replayable(node):
        if node.vjp_fn is None:
            return RuntimeError(
                f"grad node {node.name} was already released; the first "
                "backward must run with retain_graph=True (or be a "
                "create_graph=True grad) to differentiate twice")
        return NotImplementedError(
            f"create_graph=True through op '{node.name}' is not "
            "supported: the node has a custom python backward with no "
            "replayable forward (PyLayer records one automatically when "
            "its forward/backward are paddle-op based). Express the "
            "custom gradient with paddle_tpu ops, or use the functional "
            "jacobian/hessian API")

    # iterative post-order DFS over producer nodes, cut at input slots
    order: list = []            # producers before consumers
    used_slots = set()
    visited = set()
    stack = []

    def _want(node):
        if id(node) not in visited:
            visited.add(id(node))
            stack.append((node, False))

    for t in outputs:
        n = t._grad_node
        if n is not None:
            s = nodeslot_slot.get((id(n), t._out_idx))
            if s is not None:
                used_slots.add(s)
            else:
                _want(n)
        else:
            # output IS a requested leaf input: identity gradient
            s = leaf_slot.get(id(t))
            if s is not None:
                used_slots.add(s)
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if node.fwd_fn is None:
            raise _not_replayable(node)
        stack.append((node, True))
        for e in node.edges:
            if e is None:
                continue
            if e.leaf is not None:
                s = leaf_slot.get(id(e.leaf))
                if s is None:
                    # newly discovered differentiable leaf: give it a
                    # slot so the composite op links to it on the tape
                    s = len(reps)
                    leaf_slot[id(e.leaf)] = s
                    reps.append(e.leaf)
                used_slots.add(s)
            else:
                s = nodeslot_slot.get((id(e.node), e.out_idx))
                if s is not None:
                    used_slots.add(s)
                else:
                    _want(e.node)

    def F(*xs):
        def _sub(x, a):
            # the op was recorded on post-AMP-cast arrays; replay must
            # feed the same dtype (the cast is differentiable)
            if x.dtype != a.dtype and jnp.issubdtype(a.dtype, jnp.inexact):
                return x.astype(a.dtype)
            return x

        vals = {}
        for node in order:
            args = []
            for e, a in zip(node.edges, node.in_arrays):
                if e is None:
                    args.append(a)
                elif e.leaf is not None:
                    s = leaf_slot.get(id(e.leaf))
                    args.append(_sub(xs[s], a) if s is not None else a)
                else:
                    s = nodeslot_slot.get((id(e.node), e.out_idx))
                    # interior values need the same recorded-dtype cast:
                    # AMP casts BETWEEN ops (e.g. bf16 matmul feeding an
                    # fp32 reduction)
                    args.append(_sub(xs[s], a) if s is not None
                                else _sub(vals[id(e.node)][e.out_idx], a))
            out = node.fwd_fn(*args)
            vals[id(node)] = ((out,) if not isinstance(out, (tuple, list))
                              else tuple(out))
        res = []
        for t in outputs:
            n = t._grad_node
            if n is None:
                s = leaf_slot.get(id(t))
                res.append(xs[s] if s is not None else t._data)
            else:
                s = nodeslot_slot.get((id(n), t._out_idx))
                res.append(xs[s] if s is not None
                           else vals[id(n)][t._out_idx])
        return tuple(res)

    return F, slot_of, reps, used_slots


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    from paddle_tpu.core.dispatch import run_op

    for t in inputs:
        if not isinstance(t, Tensor):
            raise TypeError("grad inputs must be Tensors")
    F, slot_of, reps, used_slots = _replay_plan(outputs, inputs)
    n_slots = len(reps)
    n_req = max(slot_of) + 1 if slot_of else 0   # requested slots prefix

    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    cts = []
    for t, go in zip(outputs, grad_outputs):
        if go is None:
            cts.append(jnp.ones(t.shape, t._data.dtype))
        else:
            cts.append(go)       # Tensor keeps its tape linkage

    def gfun(*args):
        xs, ct = args[:n_slots], args[n_slots:]
        _, vjp = jax.vjp(F, *xs)
        gs = vjp(tuple(ct))
        # non-inexact primals come back as float0 — materialize zeros
        # so the results wrap cleanly (they are filtered as unused)
        return tuple(
            jnp.zeros(x.shape, x.dtype)
            if getattr(g, "dtype", None) == jax.dtypes.float0 else g
            for g, x in zip(gs[:n_req], xs[:n_req]))

    res = run_op("grad", gfun, *reps, *cts, amp=False)
    res = (res,) if not isinstance(res, tuple) else res

    result = []
    for t, s in zip(inputs, slot_of):
        if s not in used_slots:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient; pass "
                    "allow_unused=True to get None instead")
            result.append(None)
        else:
            g = res[s]
            # the requested tensors' own grad hooks fire on the result
            # (matches the tape walk); hooks on INTERIOR tensors do not
            # run under create_graph=True — the replay is functional
            for hook in t._grad_hooks:
                out = hook(g)
                if out is not None:
                    g = out if isinstance(out, Tensor) else Tensor._wrap(
                        out, stop_gradient=False)
            result.append(g)
    return result


# --------------------------------------------------------------------------
# PyLayer: user-defined autograd function (reference eager/pylayer +
# fluid/pybind/eager_py_layer.cc)
# --------------------------------------------------------------------------
#: active (pack, unpack) hook pairs for tensors saved for backward
#: (reference autograd/saved_tensors_hooks — TensorWrapper pack/unpack
#: hooks; here they intercept PyLayer save_for_backward captures)
_saved_tensors_hooks = []


class saved_tensors_hooks:
    """Context manager: pack_hook(tensor) runs when a tensor is saved
    for backward, unpack_hook(packed) when it is retrieved — the
    CPU-offload / recompute seam (reference
    python/paddle/autograd/saved_tensors_hooks.py)."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _saved_tensors_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_tensors_hooks.pop()
        return False


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._unpack = None
        self.materialize_grads = True
        self._non_differentiable = ()

    def save_for_backward(self, *tensors):
        if _saved_tensors_hooks:
            pack, unpack = _saved_tensors_hooks[-1]
            self._saved = tuple(pack(t) for t in tensors)
            self._unpack = unpack
        else:
            self._saved = tensors

    def _unpacked(self):
        if self._unpack is not None:
            return tuple(self._unpack(p) for p in self._saved)
        return self._saved

    @property
    def saved_tensor(self):
        return self._unpacked()

    def saved_tensors(self):
        return self._unpacked()

    def mark_non_differentiable(self, *tensors):
        self._non_differentiable = tensors

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


class PyLayer:
    """Subclass with static forward(ctx, *args) / backward(ctx, *grads)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        record = _dispatch.grad_enabled() and any(
            not t.stop_gradient for t in in_tensors)
        if record:
            diff_inputs = [t for t in in_tensors
                           if jnp.issubdtype(t._data.dtype, jnp.inexact)]
            nondiff_out_ids = {id(t) for t in ctx._non_differentiable}
            out_t = [t for t in out_list if isinstance(t, Tensor)]

            def vjp_fn(cotangents):
                cts = [Tensor._wrap(c, True) if not isinstance(c, Tensor)
                       else c for c in cotangents]
                with no_grad():
                    gin = cls.backward(ctx, *cts)
                gin = [gin] if isinstance(gin, Tensor) or gin is None \
                    else list(gin)
                grads = []
                gi = iter(gin)
                for t in diff_inputs:
                    g = next(gi, None)
                    grads.append(jnp.zeros(t.shape, t.dtype) if g is None
                                 else (g._data if isinstance(g, Tensor) else g))
                return tuple(grads)

            edges = []
            for t in diff_inputs:
                if t.stop_gradient:
                    edges.append(None)
                elif t._grad_node is not None:
                    edges.append(Edge(node=t._grad_node, out_idx=t._out_idx))
                else:
                    edges.append(Edge(leaf=t))
            avals = [(tuple(t.shape), t._data.dtype) for t in out_t]
            # replayable forward for create_graph=True: a jax.custom_vjp
            # whose fwd re-runs the user's forward and whose bwd is the
            # user's backward — when both are built from paddle ops they
            # are jax-traceable, and reverse-over-reverse through the
            # (traced) custom bwd gives higher-order grads, matching the
            # reference's "double grad works if backward is
            # differentiable" contract for PyLayer.
            fwd_fn = _pylayer_replay_fn(cls, args, kwargs, diff_inputs,
                                        single)
            node = GradNode(cls.__name__, vjp_fn, edges, avals,
                            fwd_fn=fwd_fn,
                            in_arrays=tuple(t._data
                                            for t in diff_inputs))
            import weakref
            for i, t in enumerate(out_t):
                if id(t) not in nondiff_out_ids:
                    t.stop_gradient = False
                    t._grad_node = node
                    t._out_idx = i
                    node.out_refs[i] = weakref.ref(t)
        return out_list[0] if single else tuple(out_list)


def _pylayer_replay_fn(cls, args, kwargs, diff_inputs, single):
    """Build the jax.custom_vjp replay of one PyLayer application.

    Takes the diff inputs' arrays positionally; every other argument
    (python values, non-differentiable tensors) is closed over by
    VALUE. Forward re-runs cls.forward with a fresh ctx (recreating
    whatever state the user's backward reads); bwd re-runs it again to
    rebuild the ctx for cls.backward — stage-level rematerialization,
    the same trade the create_graph replay makes everywhere else."""
    diff_ids = {id(t): i for i, t in enumerate(diff_inputs)}
    frozen = [a._data if isinstance(a, Tensor) else a for a in args]
    frozen_kw = {k: (v._data if isinstance(v, Tensor) else v)
                 for k, v in kwargs.items()}

    def run_forward(arrays):
        ctx2 = PyLayerContext()
        call_args = []
        for a, f in zip(args, frozen):
            if isinstance(a, Tensor) and id(a) in diff_ids:
                call_args.append(
                    Tensor._wrap(arrays[diff_ids[id(a)]], True))
            elif isinstance(a, Tensor):
                call_args.append(Tensor._wrap(f, True))
            else:
                call_args.append(a)
        call_kw = {}
        for k, v in kwargs.items():
            if isinstance(v, Tensor) and id(v) in diff_ids:
                call_kw[k] = Tensor._wrap(arrays[diff_ids[id(v)]], True)
            elif isinstance(v, Tensor):
                # snapshot by VALUE: a later optimizer rebind of the
                # tensor must not leak into the replay
                call_kw[k] = Tensor._wrap(frozen_kw[k], True)
            else:
                call_kw[k] = v
        with no_grad():
            outs = cls.forward(ctx2, *call_args, **call_kw)
        out_list = [outs] if not isinstance(outs, (tuple, list)) \
            else list(outs)
        out_arrays = tuple(t._data for t in out_list
                           if isinstance(t, Tensor))
        return ctx2, out_arrays

    def raw(*arrays):
        _, outs = run_forward(arrays)
        return outs[0] if single else outs

    f = jax.custom_vjp(raw)

    def fwd(*arrays):
        _, outs = run_forward(arrays)
        return (outs[0] if single else outs), arrays

    def bwd(res_arrays, cts):
        ctx2, _ = run_forward(res_arrays)
        ct_list = [cts] if single else list(cts)
        ct_tensors = [Tensor._wrap(c, True) for c in ct_list]
        with no_grad():
            gin = cls.backward(ctx2, *ct_tensors)
        gin = [gin] if isinstance(gin, Tensor) or gin is None \
            else list(gin)
        grads = []
        gi = iter(gin)
        for x in res_arrays:
            g = next(gi, None)
            if g is None:
                grads.append(jnp.zeros(x.shape, x.dtype))
            else:
                ga = g._data if isinstance(g, Tensor) else g
                grads.append(ga.astype(x.dtype))
        return tuple(grads)

    f.defvjp(fwd, bwd)
    return f


# --------------------------------------------------------------------------
# Functional higher-order API (paddle.autograd.jacobian / hessian) — here we
# delegate straight to jax's transforms over a wrapped pure function.
# --------------------------------------------------------------------------
def _as_pure(func):
    def pure(*arrays):
        ts = [Tensor._wrap(a, stop_gradient=False) for a in arrays]
        out = func(*ts)
        return out._data if isinstance(out, Tensor) else out
    return pure


def jacobian(func, xs, create_graph=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_l]
    jac = jax.jacrev(_as_pure(func), argnums=tuple(range(len(arrays))))(*arrays)
    outs = [Tensor._wrap(j, True) for j in jac]
    return outs[0] if not isinstance(xs, (list, tuple)) else outs


def hessian(func, xs, create_graph=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [x._data for x in xs_l]
    hes = jax.hessian(_as_pure(func), argnums=tuple(range(len(arrays))))(*arrays)
    if not isinstance(xs, (list, tuple)):
        h = hes[0][0] if isinstance(hes, (tuple, list)) else hes
        return Tensor._wrap(h, True)
    return hes
