"""Dygraph autograd engine.

Reference semantics being reproduced (paddle/fluid/eager):
  - GradNodeBase / generated <Op>GradNode  (eager/grad_node_info.h:197)
  - GradTensorHolder accumulation          (eager/grad_tensor_holder.h)
  - queue-based reverse-topological walk   (RunBackward, eager/backward.cc:105)
  - leaf accumulation + hooks              (eager/accumulation/accumulation_node.h)
  - partial-graph grad()                   (eager/general_grad.h)

TPU-native design: instead of per-op hand-written backward kernels, each node
stores the jax.vjp closure of its forward computation; residuals live in
device (HBM) buffers owned by the closure. The walk itself is host-side and
identical in structure to the reference engine, so hooks / grad accumulation /
stop_gradient semantics carry over unchanged.
"""
from __future__ import annotations

import weakref
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp


class Edge:
    """One autograd edge: where a produced input-gradient flows."""

    __slots__ = ("node", "out_idx", "leaf")

    def __init__(self, node: "GradNode" = None, out_idx: int = 0, leaf=None):
        self.node = node      # parent GradNode (producer of the input), or None
        self.out_idx = out_idx
        self.leaf = leaf      # leaf Tensor (accumulation target), or None


class GradNode:
    """Backward node for one eager op (cf. GradNodeBase, grad_node_info.h:197)."""

    __slots__ = ("name", "vjp_fn", "edges", "out_avals", "out_refs",
                 "fwd_fn", "in_arrays", "_buf", "_deps", "__weakref__")

    def __init__(self, name: str, vjp_fn, edges: List[Optional[Edge]],
                 out_avals: List[Tuple[tuple, Any]],
                 fwd_fn=None, in_arrays=None):
        self.name = name
        self.vjp_fn = vjp_fn              # cotangents -> grads for all primals
        self.edges = edges                # one entry per primal; None = no grad
        self.out_avals = out_avals        # [(shape, dtype)] per forward output
        self.out_refs: List[Optional[weakref.ref]] = [None] * len(out_avals)
        # replay captures for higher-order grad (create_graph=True):
        # the forward jax function + its recorded (post-AMP) primal
        # values — the reference's TensorWrapper captures feeding the
        # *_double_grad ops (backward.yaml:4); released with vjp_fn
        self.fwd_fn = fwd_fn
        self.in_arrays = in_arrays
        self._buf = None                  # GradTensorHolder: per-output cotangent
        self._deps = 0

    # -- execution-time helpers -------------------------------------------
    def _ensure_buf(self):
        if self._buf is None:
            self._buf = [None] * len(self.out_avals)

    def _accumulate(self, idx: int, grad):
        self._ensure_buf()
        cur = self._buf[idx]
        self._buf[idx] = grad if cur is None else cur + grad

    def _cotangents(self):
        cts = []
        for i, (shape, dtype) in enumerate(self.out_avals):
            g = self._buf[i] if self._buf is not None else None
            if g is None:
                if jnp.issubdtype(dtype, jnp.inexact):
                    g = jnp.zeros(shape, dtype)
                else:
                    g = np.zeros(shape, jax.dtypes.float0)
            elif jnp.issubdtype(dtype, jnp.inexact) and g.dtype != dtype:
                # AMP: an op downstream may accumulate its input-grad in a
                # different precision (e.g. fp32 master grads into a bf16
                # output) — vjp wants the cotangent in the output dtype
                g = g.astype(dtype)
            cts.append(g)
        return tuple(cts)

    def __repr__(self):
        return f"<GradNode {self.name} outs={len(self.out_avals)}>"


def _is_float0(g):
    return getattr(g, "dtype", None) == jax.dtypes.float0


def run_backward(tensors, grad_tensors=None, retain_graph=False,
                 targets=None, accumulate_leaf=True, allow_unused=True):
    """The reference RunBackward walk (eager/backward.cc:105).

    tensors: root Tensors; grad_tensors: matching initial cotangents (None =
    ones). If `targets` is given, behaves like GeneralGrad: returns
    {id(target): grad} and (unless accumulate_leaf) does not touch .grad.
    """
    from paddle_tpu.core.tensor import Tensor

    roots = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)
    grads = []
    for t, g in zip(roots, grad_tensors):
        if g is None:
            g = jnp.ones(t.shape, t.dtype)
        elif isinstance(g, Tensor):
            g = g._data
        grads.append(g)

    captured: Dict[int, Any] = {}
    target_by_leaf: Dict[int, Any] = {}
    target_by_slot: Dict[Tuple[int, int], Any] = {}
    if targets is not None:
        for tt in targets:
            if tt._grad_node is not None:
                target_by_slot[(id(tt._grad_node), tt._out_idx)] = tt
            else:
                target_by_leaf[id(tt)] = tt

    # ---- discovery: count in-degrees over the reachable graph ----
    root_nodes = []
    seen = set()
    stack = []
    for t in roots:
        n = t._grad_node
        if n is not None and id(n) not in seen:
            seen.add(id(n))
            stack.append(n)
            root_nodes.append(n)
    order_nodes = []
    while stack:
        n = stack.pop()
        order_nodes.append(n)
        for e in n.edges:
            if e is not None and e.node is not None:
                if id(e.node) not in seen:
                    seen.add(id(e.node))
                    e.node._deps = 0
                    stack.append(e.node)
    for n in order_nodes:
        n._deps = 0
        n._buf = None
    for n in order_nodes:
        for e in n.edges:
            if e is not None and e.node is not None:
                e.node._deps += 1

    def _leaf_accumulate(leaf, grad):
        if _is_float0(grad):
            return
        for hook in leaf._grad_hooks:
            out = hook(Tensor._wrap(grad, stop_gradient=True))
            if out is not None:
                grad = out._data if isinstance(out, Tensor) else out
        if targets is not None and id(leaf) in target_by_leaf:
            prev = captured.get(id(leaf))
            captured[id(leaf)] = grad if prev is None else prev + grad
        if accumulate_leaf:
            if leaf.grad is None:
                leaf.grad = Tensor._wrap(grad, stop_gradient=True)
            else:
                leaf.grad = Tensor._wrap(leaf.grad._data + grad,
                                         stop_gradient=True)
            for hook in leaf._post_acc_hooks:
                hook(leaf)

    # seed roots
    for t, g in zip(roots, grads):
        n = t._grad_node
        if n is None:
            if not t.stop_gradient:
                _leaf_accumulate(t, g)
            continue
        n._accumulate(t._out_idx, g)

    queue = deque(n for n in order_nodes if n._deps == 0)
    ran = set()
    while queue:
        node = queue.popleft()
        if id(node) in ran:
            continue
        ran.add(id(node))
        node._ensure_buf()
        # per-output tensor hooks (register_hook on non-leaf tensors)
        from paddle_tpu.core.flags import get_flag as _gf
        retain_all = _gf("FLAGS_retain_grad_for_all")
        for i, ref in enumerate(node.out_refs):
            if ref is None or node._buf[i] is None:
                continue
            t = ref()
            if t is not None and t._grad_hooks:
                g = node._buf[i]
                for hook in t._grad_hooks:
                    out = hook(Tensor._wrap(g, stop_gradient=True))
                    if out is not None:
                        g = out._data if isinstance(out, Tensor) else out
                node._buf[i] = g
            if retain_all and t is not None:
                # debugging: expose intermediate grads (retain_grads)
                t.grad = Tensor._wrap(node._buf[i], stop_gradient=True)
        if targets is not None:
            for i in range(len(node.out_avals)):
                tt = target_by_slot.get((id(node), i))
                if tt is not None and node._buf[i] is not None:
                    captured[id(tt)] = node._buf[i]
        if node.vjp_fn is None:
            raise RuntimeError(
                f"grad node {node.name} was already released; call "
                "backward(retain_graph=True) to backprop twice")
        in_grads = node.vjp_fn(node._cotangents())
        node._buf = None
        if not retain_graph:
            node.vjp_fn = None
            node.fwd_fn = None
            node.in_arrays = None
        for e, g in zip(node.edges, in_grads):
            if e is None or _is_float0(g):
                continue
            if e.node is not None:
                e.node._accumulate(e.out_idx, g)
                e.node._deps -= 1
                if e.node._deps == 0:
                    queue.append(e.node)
            elif e.leaf is not None:
                leaf = e.leaf
                if not leaf.stop_gradient:
                    _leaf_accumulate(leaf, g)
        # parents that received no gradient contribution from this node still
        # need their dep count reduced for float0/None edges
        for e, g in zip(node.edges, in_grads):
            if e is not None and e.node is not None and _is_float0(g):
                e.node._deps -= 1
                if e.node._deps == 0:
                    queue.append(e.node)

    if targets is not None:
        return captured
    return None
