"""paddle.base compatibility namespace (reference: python/paddle/base —
the legacy fluid core). Re-exports the modern equivalents so code doing
`from paddle.base import core` or `paddle.base.framework` keeps working."""
from paddle_tpu.framework import core  # noqa: F401
from paddle_tpu import framework  # noqa: F401
from paddle_tpu.static import (  # noqa: F401
    Executor, Program, default_main_program, default_startup_program,
    global_scope, program_guard, scope_guard,
)
from paddle_tpu.core.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, TPUPlace,
)
from paddle_tpu.core.tensor import Tensor  # noqa: F401
from paddle_tpu.nn.layer.layers import ParamAttr  # noqa: F401


def in_dygraph_mode():
    return True


def in_pir_mode():
    return False
