"""paddle.batch equivalent (reference: python/paddle/batch.py:26) —
wrap an item-reader generator into a batched reader."""
from __future__ import annotations


def batch(reader, batch_size, drop_last=False):
    if batch_size <= 0:
        raise ValueError("batch_size should be a positive integer value, "
                         f"but got batch_size={batch_size}")

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
