"""paddle.check_import_scipy equivalent (reference: a Windows DLL-error
diagnostic around `import scipy`)."""


def check_import_scipy(os_name):
    if os_name == 'nt':
        try:
            import scipy.io  # noqa: F401
        except ImportError as e:
            if 'DLL load failed' in str(e):
                raise ImportError(
                    "Error: import scipy.io failed; please check your "
                    "Visual C++ runtime installation")
    return True
