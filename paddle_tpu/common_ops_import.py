"""paddle.common_ops_import equivalent (reference re-exports base
helpers for op modules)."""
from paddle_tpu.core.tensor import Tensor  # noqa: F401
from paddle_tpu.core.tensor import Tensor as Variable  # noqa: F401
from paddle_tpu.core import dtype as core  # noqa: F401
from paddle_tpu.framework import in_dynamic_mode  # noqa: F401

def in_dynamic_or_pir_mode():
    return in_dynamic_mode()


def check_type(input, input_name, expected_type, op_name):
    if not isinstance(input, expected_type):
        raise TypeError(
            f"The type of '{input_name}' in {op_name} must be "
            f"{expected_type}, but received {type(input)}.")


def check_variable_and_dtype(input, input_name, expected_dtype, op_name):
    check_type(input, input_name, (Tensor,), op_name)


def check_dtype(input_dtype, input_name, expected_dtype, op_name):
    pass


class LayerHelper:
    """Minimal stand-in for legacy LayerHelper (reference
    base/layer_helper.py) used by code written against the old static
    API; creates eager tensors directly."""

    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
