"""jax version compatibility seams.

The manual-axes parallel stack (compiled pipelines, manual-tp,
collective matmuls) is written against the modern jax surface:
top-level ``jax.shard_map`` plus the varying-manual-axes type system
(``lax.pcast`` / ``jax.typeof(...).vma``). Older jax (< 0.6) only has
``jax.experimental.shard_map`` and no vma tracking at all — there is
no faithful emulation of pcast there, so this module does NOT try:

* ``shard_map``: the real function wherever it lives. On old jax the
  experimental one is re-signatured to accept/ignore ``check_vma``
  (mapped onto ``check_rep=False`` — without vma types replication
  checking rejects the pipeline bodies).
* ``HAS_MANUAL_AXES``: capability flag — True when the vma type system
  (``lax.pcast``) exists, i.e. when the compiled-pipeline /manual-tp
  paths can actually trace. Callers (and tests) gate on this instead
  of crashing mid-trace with an AttributeError.
"""
from __future__ import annotations

import jax
from jax import lax

#: the varying-manual-axes type system the compiled pipelines need
HAS_MANUAL_AXES: bool = hasattr(lax, "pcast")

try:
    from jax import shard_map  # modern jax: top-level function
except ImportError:            # pragma: no cover - depends on jax build
    from jax.experimental.shard_map import shard_map as _esm

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kw):
        """Old-jax fallback: experimental shard_map, check_vma→check_rep
        (False: no vma types to check against), axis_names→auto (the
        complement set, experimental's way of leaving axes automatic)."""
        kw.pop("check_vma", None)
        kw.setdefault("check_rep", False)
        names = kw.pop("axis_names", None)
        if names is not None and mesh is not None:
            kw.setdefault("auto",
                          frozenset(mesh.axis_names) - frozenset(names))
        return _esm(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, **kw)
