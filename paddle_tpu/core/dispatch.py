"""Eager op dispatch.

Reference call path being reproduced (SURVEY §3.1): the generated
`<op>_ad_func` layer — AMP auto-cast (eager_gen.py:588) → kernel selection +
launch (api_base.py:452) → GradNode creation + TensorWrapper capture
(eager_gen.py:1127).

TPU-native design: the "kernel" is a jnp/lax function; XLA's per-primitive
dispatch cache plays the role of the KernelFactory (phi/core/kernel_factory.h).
When any input requires grad, the forward runs under jax.vjp and the returned
closure *is* the GradNode's backward (residuals = TensorWrapper captures).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor
from .flags import get_flag
from paddle_tpu.autograd.tape import Edge, GradNode
from paddle_tpu.observability import metrics as _met

# eager-dispatch telemetry (observability layer): one counter, cached at
# import — the hot path pays a single `_met._ENABLED` branch when off
_op_dispatches = _met.REGISTRY.counter("eager.op_dispatches")
_op_grad_recorded = _met.REGISTRY.counter("eager.grad_ops")

# --- global eager state (reference: egr::Controller / imperative::Tracer) ---
_grad_enabled = True
# AMP hook installed by paddle_tpu.amp: fn(op_name, arrays) -> arrays
_amp_hook: Optional[Callable] = None
# per-op observer hooks (profiler / nan check attach here)
_op_observers = []


def grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(flag: bool) -> bool:
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = bool(flag)
    return prev


def set_amp_hook(hook):
    global _amp_hook
    _amp_hook = hook


def add_op_observer(cb):
    _op_observers.append(cb)
    return lambda: _op_observers.remove(cb)


def _check_nan_inf(name, arrays, in_arrays=()):
    level = get_flag("FLAGS_check_nan_inf_level")
    for a in arrays:
        if not jnp.issubdtype(a.dtype, jnp.inexact):
            continue
        try:
            bad = bool(~jnp.isfinite(a).all())
        except Exception:
            return  # tracer — checked at runtime only in eager mode
        if bad:
            msg = f"NaN/Inf detected in output of op '{name}'"
            dump_dir = get_flag("FLAGS_nan_inf_dump_dir")
            if dump_dir:
                # dump the offending op's operands for post-mortem
                # (check_nan_inf_level dump behavior in the reference)
                import os
                import time as _time
                import numpy as _np
                os.makedirs(dump_dir, exist_ok=True)
                path = os.path.join(
                    dump_dir, f"naninf_{name}_{int(_time.time()*1e3)}")
                _np.savez(path,
                          **{f"in{i}": _np.asarray(x)
                             for i, x in enumerate(in_arrays)},
                          **{f"out{i}": _np.asarray(x)
                             for i, x in enumerate(arrays)})
                msg += f" (operands dumped to {path}.npz)"
            if level >= 3:
                print("[check_nan_inf]", msg)
            else:
                raise FloatingPointError(msg)


def _differentiable(t: Tensor) -> bool:
    return (not t.stop_gradient) and jnp.issubdtype(t._data.dtype, jnp.inexact)


def run_op(name: str, fn: Callable, *inputs, n_outputs=None, amp=True,
           out_stop_gradient=None, differentiable=True):
    """Execute one eager op.

    fn takes raw jax arrays (same arity as `inputs`) and returns an array or
    a tuple of arrays. Tensor inputs are unwrapped; non-Tensor inputs are
    converted with jnp.asarray.
    """
    if _met._ENABLED:
        _op_dispatches.inc()
    arrays = []
    in_tensors = []
    for x in inputs:
        if isinstance(x, Tensor):
            arrays.append(x._data)
            in_tensors.append(x)
        else:
            arrays.append(x if isinstance(x, jax.Array) else jnp.asarray(x))
            in_tensors.append(None)

    if amp and _amp_hook is not None:
        arrays = _amp_hook(name, arrays)

    needs = [t is not None and _differentiable(t) for t in in_tensors]
    record = differentiable and _grad_enabled and any(needs)

    try:
        if record:
            out_arrays, vjp_fn = jax.vjp(fn, *arrays)
        else:
            out_arrays = fn(*arrays)
    except Exception as e:
        if get_flag("FLAGS_call_stack_level") >= 2:
            sig = ", ".join(f"{a.dtype}{list(a.shape)}" for a in arrays)
            raise RuntimeError(
                f"op '{name}' failed (inputs: {sig}): "
                f"{type(e).__name__}: {e}") from e
        raise

    single = not isinstance(out_arrays, (tuple, list))
    outs = (out_arrays,) if single else tuple(out_arrays)

    if get_flag("FLAGS_op_log"):
        filt = get_flag("FLAGS_op_log_filter")
        if not filt or filt in (name or ""):
            import sys as _sys
            ins = ",".join(f"{a.dtype}{list(a.shape)}" for a in arrays)
            os_ = ",".join(f"{a.dtype}{list(a.shape)}" for a in outs)
            print(f"[op] {name}({ins}) -> {os_}", file=_sys.stderr)

    if get_flag("FLAGS_check_nan_inf"):
        _check_nan_inf(name, outs, arrays)
    for cb in _op_observers:
        cb(name, outs)

    sg = not record if out_stop_gradient is None else out_stop_gradient
    out_tensors = [Tensor._wrap(a, stop_gradient=sg) for a in outs]

    if record:
        if _met._ENABLED:
            _op_grad_recorded.inc()
        edges = []
        for t, need in zip(in_tensors, needs):
            if not need:
                edges.append(None)
            elif t._grad_node is not None:
                edges.append(Edge(node=t._grad_node, out_idx=t._out_idx))
            else:
                edges.append(Edge(leaf=t))
        avals = [(tuple(a.shape), a.dtype) for a in outs]
        if single:
            # jax.vjp's closure wants the cotangent in the same structure
            # as f's output (bare array, not 1-tuple)
            inner_vjp = vjp_fn
            vjp_fn = lambda cts: inner_vjp(cts[0])  # noqa: E731
        node = GradNode(name, vjp_fn, edges, avals,
                        fwd_fn=fn, in_arrays=tuple(arrays))
        import weakref
        for i, ot in enumerate(out_tensors):
            if not ot.stop_gradient:
                ot._grad_node = node
                ot._out_idx = i
                node.out_refs[i] = weakref.ref(ot)

    return out_tensors[0] if single else tuple(out_tensors)


def rebind_inplace(target: Tensor, res: Tensor) -> Tensor:
    """Rebind `res`'s buffer + autograd node onto `target` (the tail of
    every inplace op: ops.yaml `inplace:` semantics on immutable XLA
    buffers). Shared by run_op_inplace and the generated `<op>_` family."""
    target._assign_array(res._data)
    # the result of an inplace op participates in autograd via the new node
    target._grad_node = res._grad_node
    target._out_idx = res._out_idx
    target.stop_gradient = res.stop_gradient and target.stop_gradient
    if res._grad_node is not None:
        import weakref
        res._grad_node.out_refs[res._out_idx] = weakref.ref(target)
    return target


def run_op_inplace(name: str, fn: Callable, target: Tensor, *extra_inputs,
                   **kw):
    """Inplace op: computes fn(target, *extra) then rebinds target's buffer
    (ops.yaml `inplace:` semantics on immutable XLA buffers)."""
    out = run_op(name, fn, target, *extra_inputs, **kw)
    res = out[0] if isinstance(out, tuple) else out
    return rebind_inplace(target, res)
