"""Dtype system.

TPU-native replacement for the reference dtype library
(paddle/phi/common/{data_type.h,bfloat16.h,float16.h,type_promotion.h}).
Instead of hand-rolled device-portable scalar types, dtypes are numpy/ml_dtypes
dtype objects (XLA understands these natively); promotion delegates to JAX's
promotion lattice which matches the reference promoteTypes table
(phi/common/type_promotion.h:53) for the types both support.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects (usable anywhere a dtype is accepted).
bool_ = np.dtype(np.bool_)
uint8 = np.dtype(np.uint8)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
float16 = np.dtype(np.float16)
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "fp16": float16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_DEFAULT_DTYPE = float32


def convert_dtype(dtype):
    """Normalize any dtype spec (str / np.dtype / type) to a np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, np.dtype):
        return dtype
    if isinstance(dtype, str):
        key = dtype.lower().replace("paddle.", "")
        if key in _ALIASES:
            return _ALIASES[key]
    return np.dtype(dtype)


_X64_DOWNGRADE = {
    np.dtype(np.int64): np.dtype(np.int32),
    np.dtype(np.uint64): np.dtype(np.uint32),
    np.dtype(np.float64): np.dtype(np.float32),
    np.dtype(np.complex128): np.dtype(np.complex64),
}


def jax_dtype(dtype):
    """The dtype XLA will actually store for `dtype`: with jax x64
    disabled (the default), 64-bit requests downgrade to their 32-bit
    storage type — done here EXPLICITLY so the paddle API surface keeps
    accepting int64/float64 without tripping jax's per-call truncation
    warning, and so flipping jax_enable_x64 gives true 64-bit behavior
    (VERDICT r2 weak #10: the implicit truncations were warning-spam at
    best and silent dtype bugs under x64)."""
    d = convert_dtype(dtype)
    if d is None:
        return None
    import jax
    if not jax.config.read("jax_enable_x64"):
        return _X64_DOWNGRADE.get(d, d)
    return d


def set_default_dtype(dtype):
    global _DEFAULT_DTYPE
    d = convert_dtype(dtype)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(
            "set_default_dtype only supports float16/bfloat16/float32/float64, "
            f"got {d}"
        )
    _DEFAULT_DTYPE = d


def get_default_dtype():
    return _DEFAULT_DTYPE


def is_floating_point(dtype):
    d = convert_dtype(dtype)
    return d in (float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2)


def is_integer(dtype):
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.integer) or d == bool_


def is_complex(dtype):
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.complexfloating)


def promote_types(a, b):
    """Binary dtype promotion (reference: phi/common/type_promotion.h:53)."""
    return np.dtype(jnp.promote_types(convert_dtype(a), convert_dtype(b)))


def finfo(dtype):
    return ml_dtypes.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return np.iinfo(convert_dtype(dtype))
