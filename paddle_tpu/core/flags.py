"""Runtime flag registry (reference: paddle/common/flags.cc, 178 flags;
PD_DEFINE_* macros flags.h:38; exported to Python as paddle.set_flags /
FLAGS_* env vars).

TPU-native version: a typed Python registry with env-var override at
definition time. Native-side knobs map onto XLA_FLAGS, which XLA itself owns.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    value: Any
    help: str
    type: type


_REGISTRY: Dict[str, _Flag] = {}
_OBSERVERS: Dict[str, Callable[[Any], None]] = {}


def _coerce(ty, raw):
    if ty is bool:
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return ty(raw)


def define_flag(name: str, default, help: str = ""):
    ty = type(default)
    raw = os.environ.get(name, None)
    value = _coerce(ty, raw) if raw is not None else default
    _REGISTRY[name] = _Flag(name, default, value, help, ty)
    return value


def get_flags(names=None):
    if names is None:
        names = list(_REGISTRY)
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[n].value for n in names}


def get_flag(name: str):
    return _REGISTRY[name].value


def set_flags(flags: Dict[str, Any]):
    for name, v in flags.items():
        if name not in _REGISTRY:
            raise KeyError(f"unknown flag {name!r}")
        f = _REGISTRY[name]
        f.value = _coerce(f.type, v)
        cb = _OBSERVERS.get(name)
        if cb is not None:
            cb(f.value)


def on_flag_change(name: str, cb: Callable[[Any], None]):
    _OBSERVERS[name] = cb


# Core flags (subset of paddle/common/flags.cc the TPU build honors).
define_flag("FLAGS_check_nan_inf", False,
            "scan every op output for NaN/Inf (flags.cc:72 equivalent)")
define_flag("FLAGS_check_nan_inf_level", 0,
            "0: raise on nan/inf; 3: log only")
define_flag("FLAGS_benchmark", False, "block on every op for timing")
define_flag("FLAGS_log_level", 0, "framework verbosity")
define_flag("FLAGS_eager_op_cache", True,
            "cache per-op compiled executables in eager mode")
define_flag("FLAGS_kv_capacity_check", True,
            "eager KV-cache overflow guard in the decode path (one tiny "
            "device sync per eager step; traced/serving paths unaffected)")
define_flag("FLAGS_collective_matmul", False,
            "SP linears use ring-overlapped collective matmuls "
            "(all_gather@W / X@W->reduce_scatter) instead of GSPMD "
            "constraint resharding")
define_flag("FLAGS_collective_timeout_s", 600.0,
            "collective watchdog timeout seconds")
