"""Runtime flag registry (reference: paddle/common/flags.cc, 178 flags;
PD_DEFINE_* macros flags.h:38; exported to Python as paddle.set_flags /
FLAGS_* env vars).

TPU-native version: a typed Python registry with env-var override at
definition time. Native-side knobs map onto XLA_FLAGS, which XLA itself owns.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    value: Any
    help: str
    type: type


_REGISTRY: Dict[str, _Flag] = {}
_OBSERVERS: Dict[str, Callable[[Any], None]] = {}


def _coerce(ty, raw):
    if ty is bool:
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return ty(raw)


def define_flag(name: str, default, help: str = ""):
    ty = type(default)
    raw = os.environ.get(name, None)
    value = _coerce(ty, raw) if raw is not None else default
    _REGISTRY[name] = _Flag(name, default, value, help, ty)
    return value


def get_flags(names=None):
    if names is None:
        names = list(_REGISTRY)
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[n].value for n in names}


def get_flag(name: str):
    return _REGISTRY[name].value


def set_flags(flags: Dict[str, Any]):
    for name, v in flags.items():
        if name not in _REGISTRY:
            raise KeyError(f"unknown flag {name!r}")
        f = _REGISTRY[name]
        f.value = _coerce(f.type, v)
        cb = _OBSERVERS.get(name)
        if cb is not None:
            cb(f.value)


def on_flag_change(name: str, cb: Callable[[Any], None]):
    _OBSERVERS[name] = cb


# Core flags (subset of paddle/common/flags.cc the TPU build honors).
define_flag("FLAGS_check_nan_inf", False,
            "scan every op output for NaN/Inf (flags.cc:72 equivalent)")
define_flag("FLAGS_check_nan_inf_level", 0,
            "0: raise on nan/inf; 3: log only")
define_flag("FLAGS_benchmark", False, "block on every op for timing")
define_flag("FLAGS_log_level", 0, "framework verbosity")
define_flag("FLAGS_eager_op_cache", True,
            "cache per-op compiled executables in eager mode")
define_flag("FLAGS_kv_capacity_check", True,
            "eager KV-cache overflow guard in the decode path (one tiny "
            "device sync per eager step; traced/serving paths unaffected)")
define_flag("FLAGS_collective_matmul", False,
            "SP linears use ring-overlapped collective matmuls "
            "(all_gather@W / X@W->reduce_scatter) instead of GSPMD "
            "constraint resharding")
define_flag("FLAGS_collective_timeout_s", 600.0,
            "collective watchdog timeout seconds")

# --- debugging / determinism surface (round 3: the actionable subset of
# the reference's 178 flags, each with a real effect + an effect test in
# tests/test_flags_effects.py) -------------------------------------------
define_flag("FLAGS_deterministic", False,
            "deterministic mode (FLAGS_cudnn_deterministic analog): "
            "attention autotune uses the static config (no measured "
            "selection), matmul precision pinned to 'highest'")
define_flag("FLAGS_matmul_precision", "",
            "'default'|'high'|'highest' -> jax default_matmul_precision "
            "(applied on set)")
define_flag("FLAGS_op_log", False,
            "log every eager op dispatch with dtypes/shapes (the VLOG "
            "api-trace analog); see FLAGS_op_log_filter")
define_flag("FLAGS_op_log_filter", "",
            "substring filter for FLAGS_op_log (empty = all ops)")
define_flag("FLAGS_nan_inf_dump_dir", "",
            "when FLAGS_check_nan_inf trips, dump the offending op's "
            "inputs/outputs as npz here before raising "
            "(check_nan_inf_level dump behavior)")
define_flag("FLAGS_collective_debug", False,
            "log every eager collective call (op, group, shape) — the "
            "NCCL_DEBUG analog")
define_flag("FLAGS_watchdog_interval_s", 10.0,
            "collective watchdog probe interval")
define_flag("FLAGS_step_timeout_s", 1800.0,
            "train-step stall watchdog timeout (TrainStepWatchdog "
            "default): a step exceeding it is aborted with a "
            "straggler report instead of hanging silently")
define_flag("FLAGS_max_bad_steps", 5,
            "consecutive non-finite/skipped train steps before the "
            "StepGuard circuit breaker aborts the run")
define_flag("FLAGS_watchdog_store_root", "",
            "shared dir for cross-rank watchdog progress exchange; when "
            "set, a timeout dump names the straggler rank(s)")
define_flag("FLAGS_print_jaxpr", False,
            "print the traced jaxpr when to_static builds a program "
            "(FLAGS_print_ir analog)")
define_flag("FLAGS_max_specializations", 8,
            "cap on cached to_static specializations per signature "
            "before eager fallback")
define_flag("FLAGS_max_shape_specializations", 8,
            "cap on distinct dynamic-dim (InputSpec None) shapes a "
            "to_static fn compiles before new shapes run eagerly "
            "(the shape-dialect surface's executable budget)")
define_flag("FLAGS_retain_grad_for_all", False,
            "keep .grad on non-leaf tensors after backward (debugging; "
            "the retain_grads analog)")
define_flag("FLAGS_call_stack_level", 1,
            ">=2: eager op errors are wrapped with the op name and "
            "input dtypes/shapes (flags.cc call_stack_level)")
define_flag("FLAGS_memory_stats_dump_path", "",
            "paddle.device.dump_memory_stats() target; also dumped by "
            "the watchdog on timeout when set")
define_flag("FLAGS_tensor_print_precision", 6,
            "digits in Tensor repr (set_printoptions analog)")
define_flag("FLAGS_tensor_print_threshold", 1000,
            "summarize Tensor repr beyond this many elements")
define_flag("FLAGS_low_precision_op_list", False,
            "record op names auto-cast by AMP; read with "
            "paddle.amp.debugging.get_low_precision_op_list()")
define_flag("FLAGS_allocator_strategy", "auto_growth",
            "'auto_growth' -> XLA_PYTHON_CLIENT_ALLOCATOR=default, "
            "'naive_best_fit' -> =platform; honored at import (XLA "
            "owns the allocator after backend init)")


def _allocator_env(strategy: str) -> str:
    """Map the reference allocator strategy names onto the XLA client
    allocator (XLA owns allocation after backend init — honored only
    when exported before the first device op)."""
    return {"auto_growth": "default",
            "naive_best_fit": "platform"}.get(strategy, "default")


def _apply_matmul_precision(v):
    import jax
    if get_flag("FLAGS_deterministic"):
        return          # deterministic pin wins until it is disabled
    jax.config.update("jax_default_matmul_precision", v or None)


def _apply_deterministic(v):
    import jax
    if v:
        jax.config.update("jax_default_matmul_precision", "highest")
    else:
        # restore the explicit FLAGS_matmul_precision choice (or the
        # jax default) — disabling determinism must not leave the
        # precision silently pinned
        jax.config.update("jax_default_matmul_precision",
                          get_flag("FLAGS_matmul_precision") or None)


def _apply_allocator(v):
    os.environ["XLA_PYTHON_CLIENT_ALLOCATOR"] = _allocator_env(v)


on_flag_change("FLAGS_matmul_precision", _apply_matmul_precision)
on_flag_change("FLAGS_deterministic", _apply_deterministic)
on_flag_change("FLAGS_allocator_strategy", _apply_allocator)

# env-set flags apply their side effects at import too
if os.environ.get("FLAGS_matmul_precision"):
    _apply_matmul_precision(get_flag("FLAGS_matmul_precision"))
if os.environ.get("FLAGS_deterministic") and \
        get_flag("FLAGS_deterministic"):
    _apply_deterministic(True)
if os.environ.get("FLAGS_allocator_strategy"):
    _apply_allocator(get_flag("FLAGS_allocator_strategy"))
