"""RNG state (reference: phi/core/generator.h — per-device generator with
seed + offset-based philox).

TPU-native: JAX threefry keys. The reference's (seed, offset) pair maps to
(seed key, fold_in counter): every random op consumes `fold_in(key, offset++)`,
which is the same splittable-counter discipline phi uses for philox offsets and
is safe under jit (the counter is read at trace time; traced programs get a key
argument instead — see paddle_tpu.jit).

Key creation is LAZY: `jax.random.PRNGKey` initializes the device backend,
and `import paddle_tpu` must not touch devices (host-only tools — the
launcher, dataset workers — import the package with no accelerator).
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._key = None          # materialized on first use
            self._offset = 0
        return self

    def seed(self):
        return self._seed

    def get_state(self):
        return {"seed": self._seed, "offset": self._offset}

    def set_state(self, state):
        self._seed = int(state["seed"])
        self._key = None
        self._offset = int(state["offset"])

    def _base_key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    def next_key(self):
        """One fresh PRNG key; bumps the offset (philox-offset equivalent)."""
        with self._lock:
            off = self._offset
            self._offset += 1
        return jax.random.fold_in(self._base_key(), off)

    def initial_seed(self):
        return self._seed


_DEFAULT = Generator(seed=np.random.randint(0, 2**31 - 1))


def default_generator() -> Generator:
    return _DEFAULT


def seed(s: int) -> Generator:
    """paddle.seed equivalent: reseed the default generator."""
    _DEFAULT.manual_seed(s)
    return _DEFAULT


def next_key():
    return _DEFAULT.next_key()
