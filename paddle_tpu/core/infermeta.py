"""InferMeta: shape/dtype/layout inference shared by dygraph and static IR.

Reference: paddle/phi/infermeta/{unary,binary,ternary,multiary}.cc +
MetaTensor (phi/core/meta_tensor.h). The reference hand-writes one C++
shape function per op (47.6k LoC); the TPU-native design keeps explicit
meta functions only for the ops whose shape logic the static IR needs
without tracing, and delegates everything else to XLA abstract evaluation
(`jax.eval_shape`), which *is* the compiler's own infermeta.

Used by:
  - the static IR tracer (paddle_tpu.ir) to stamp Value types;
  - tests/test_op_schema.py to cross-check every explicit meta function
    against jax.eval_shape on sample shapes.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax


class MetaTensor:
    """Shape+dtype handle (phi/core/meta_tensor.h analog)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape: Sequence[int], dtype):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)

    @classmethod
    def from_array(cls, a) -> "MetaTensor":
        return cls(a.shape, a.dtype)

    def numel(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def __repr__(self):
        return f"MetaTensor({list(self.shape)}, {self.dtype.name})"

    def __eq__(self, other):
        return (isinstance(other, MetaTensor) and self.shape == other.shape
                and self.dtype == other.dtype)


# ------------------------------------------------------------------ helpers

def broadcast_shape(a: Sequence[int], b: Sequence[int]) -> Tuple[int, ...]:
    """NumPy-style broadcast of two shapes (phi funcs.h GetBroadcastDims)."""
    ra, rb = list(a)[::-1], list(b)[::-1]
    out = []
    for i in range(max(len(ra), len(rb))):
        da = ra[i] if i < len(ra) else 1
        db = rb[i] if i < len(rb) else 1
        if da == db or da == 1 or db == 1:
            out.append(max(da, db))
        else:
            raise ValueError(f"cannot broadcast {tuple(a)} and {tuple(b)}")
    return tuple(out[::-1])


def _norm_axis(axis: int, ndim: int) -> int:
    if axis < -ndim or (ndim > 0 and axis >= ndim):
        raise ValueError(f"axis {axis} out of range for ndim {ndim}")
    return axis + ndim if axis < 0 else axis


# ---------------------------------------------------------------- unary ops

def unchanged_infermeta(x: MetaTensor) -> MetaTensor:
    """UnchangedInferMeta (phi/infermeta/unary.cc)."""
    return MetaTensor(x.shape, x.dtype)


def cast_infermeta(x: MetaTensor, dtype) -> MetaTensor:
    return MetaTensor(x.shape, dtype)


def real_to_complex_map(dt):
    return {np.dtype(np.float32): np.dtype(np.complex64),
            np.dtype(np.float64): np.dtype(np.complex128)}.get(
                np.dtype(dt), np.dtype(dt))


def complex_to_real_map(dt):
    return {np.dtype(np.complex64): np.dtype(np.float32),
            np.dtype(np.complex128): np.dtype(np.float64)}.get(
                np.dtype(dt), np.dtype(dt))


def reduce_infermeta(x: MetaTensor, axis=None, keepdim=False,
                     dtype=None) -> MetaTensor:
    """ReduceInferMeta / SumInferMeta."""
    dt = np.dtype(dtype) if dtype is not None else x.dtype
    if axis is None:
        shape = tuple([1] * len(x.shape)) if keepdim else ()
        return MetaTensor(shape, dt)
    axes = [axis] if isinstance(axis, int) else list(axis)
    axes = sorted(_norm_axis(a, len(x.shape)) for a in axes)
    out = []
    for i, s in enumerate(x.shape):
        if i in axes:
            if keepdim:
                out.append(1)
        else:
            out.append(s)
    return MetaTensor(out, dt)


def argminmax_infermeta(x: MetaTensor, axis=None, keepdim=False,
                        dtype=np.int64) -> MetaTensor:
    if axis is None:
        return MetaTensor((), np.dtype(dtype))
    m = reduce_infermeta(x, axis, keepdim)
    return MetaTensor(m.shape, np.dtype(dtype))


def reshape_infermeta(x: MetaTensor, shape: Sequence[int]) -> MetaTensor:
    """ReshapeInferMeta: supports one -1 and 0 ("copy input dim")."""
    shape = list(shape)
    for i, s in enumerate(shape):
        if s == 0:
            if i >= len(x.shape):
                raise ValueError("0-dim index out of range in reshape")
            shape[i] = x.shape[i]
    negs = [i for i, s in enumerate(shape) if s == -1]
    if len(negs) > 1:
        raise ValueError("only one -1 allowed in reshape target")
    if negs:
        known = int(np.prod([s for s in shape if s != -1])) or 1
        shape[negs[0]] = x.numel() // known
    if int(np.prod(shape) if shape else 1) != x.numel():
        raise ValueError(f"reshape {x.shape}->{shape}: numel mismatch")
    return MetaTensor(shape, x.dtype)


def transpose_infermeta(x: MetaTensor, perm: Sequence[int]) -> MetaTensor:
    perm = [_norm_axis(p, len(x.shape)) for p in perm]
    if sorted(perm) != list(range(len(x.shape))):
        raise ValueError(f"invalid perm {perm} for shape {x.shape}")
    return MetaTensor([x.shape[p] for p in perm], x.dtype)


def flatten_infermeta(x: MetaTensor, start_axis=0, stop_axis=-1) -> MetaTensor:
    nd = len(x.shape)
    if nd == 0:
        return MetaTensor((1,), x.dtype)
    a = _norm_axis(start_axis, nd)
    b = _norm_axis(stop_axis, nd)
    mid = int(np.prod(x.shape[a:b + 1])) if b >= a else 1
    return MetaTensor(x.shape[:a] + (mid,) + x.shape[b + 1:], x.dtype)


def squeeze_infermeta(x: MetaTensor, axis=None) -> MetaTensor:
    if axis is None:
        return MetaTensor([s for s in x.shape if s != 1], x.dtype)
    axes = [axis] if isinstance(axis, int) else list(axis)
    axes = {_norm_axis(a, len(x.shape)) for a in axes}
    out = [s for i, s in enumerate(x.shape) if not (i in axes and s == 1)]
    return MetaTensor(out, x.dtype)


def unsqueeze_infermeta(x: MetaTensor, axis) -> MetaTensor:
    axes = [axis] if isinstance(axis, int) else list(axis)
    out = list(x.shape)
    for a in sorted(_norm_axis(a, len(out) + 1) for a in axes):
        out.insert(a, 1)
    return MetaTensor(out, x.dtype)


def expand_infermeta(x: MetaTensor, shape: Sequence[int]) -> MetaTensor:
    out = list(shape)
    offset = len(out) - len(x.shape)
    for i, s in enumerate(out):
        if s == -1:
            j = i - offset
            if j < 0:
                raise ValueError("cannot infer -1 expand dim")
            out[i] = x.shape[j]
    broadcast_shape(x.shape, out)  # validates
    return MetaTensor(out, x.dtype)


def tile_infermeta(x: MetaTensor, repeat_times: Sequence[int]) -> MetaTensor:
    rt = list(repeat_times)
    shape = list(x.shape)
    if len(rt) < len(shape):
        rt = [1] * (len(shape) - len(rt)) + rt
    if len(shape) < len(rt):
        shape = [1] * (len(rt) - len(shape)) + shape
    return MetaTensor([s * r for s, r in zip(shape, rt)], x.dtype)


def pad_infermeta(x: MetaTensor, paddings: Sequence[int]) -> MetaTensor:
    """pad with [before0, after0, before1, after1, ...] (paddle order)."""
    out = list(x.shape)
    for i in range(len(paddings) // 2):
        out[i] += paddings[2 * i] + paddings[2 * i + 1]
    return MetaTensor(out, x.dtype)


def slice_infermeta(x: MetaTensor, axes, starts, ends) -> MetaTensor:
    out = list(x.shape)
    for ax, st, en in zip(axes, starts, ends):
        ax = _norm_axis(ax, len(out))
        n = out[ax]
        st = max(0, st + n if st < 0 else st)
        en = min(n, en + n if en < 0 else en)
        out[ax] = max(0, en - st)
    return MetaTensor(out, x.dtype)


# --------------------------------------------------------------- binary ops

def elementwise_infermeta(x: MetaTensor, y: MetaTensor) -> MetaTensor:
    """ElementwiseInferMeta: broadcast + dtype promotion."""
    from . import dtype as dtype_mod
    shape = broadcast_shape(x.shape, y.shape)
    dt = dtype_mod.promote_types(x.dtype, y.dtype) \
        if x.dtype != y.dtype else x.dtype
    return MetaTensor(shape, dt)


def compare_infermeta(x: MetaTensor, y: MetaTensor) -> MetaTensor:
    return MetaTensor(broadcast_shape(x.shape, y.shape), np.bool_)


def matmul_infermeta(x: MetaTensor, y: MetaTensor, transpose_x=False,
                     transpose_y=False) -> MetaTensor:
    """MatmulInferMeta (phi/infermeta/binary.cc)."""
    xs, ys = list(x.shape), list(y.shape)
    vec_x = len(xs) == 1
    vec_y = len(ys) == 1
    if vec_x:
        xs = [1, xs[0]] if not transpose_x else [xs[0], 1]
    if vec_y:
        ys = [ys[0], 1] if not transpose_y else [1, ys[0]]
    if transpose_x:
        xs[-2], xs[-1] = xs[-1], xs[-2]
    if transpose_y:
        ys[-2], ys[-1] = ys[-1], ys[-2]
    if xs[-1] != ys[-2]:
        raise ValueError(f"matmul K mismatch: {x.shape} @ {y.shape}")
    batch = broadcast_shape(xs[:-2], ys[:-2])
    out = list(batch) + [xs[-2], ys[-1]]
    if vec_x:
        out.pop(-2)
    if vec_y:
        out.pop(-1)
    from . import dtype as dtype_mod
    dt = dtype_mod.promote_types(x.dtype, y.dtype) \
        if x.dtype != y.dtype else x.dtype
    return MetaTensor(out, dt)


def embedding_infermeta(ids: MetaTensor, weight: MetaTensor) -> MetaTensor:
    return MetaTensor(ids.shape + (weight.shape[-1],), weight.dtype)


def gather_infermeta(x: MetaTensor, index: MetaTensor, axis=0) -> MetaTensor:
    ax = _norm_axis(axis, len(x.shape))
    out = list(x.shape)
    out[ax:ax + 1] = list(index.shape)
    return MetaTensor(out, x.dtype)


def index_select_infermeta(x: MetaTensor, index: MetaTensor,
                           axis=0) -> MetaTensor:
    ax = _norm_axis(axis, len(x.shape))
    out = list(x.shape)
    out[ax] = index.shape[0]
    return MetaTensor(out, x.dtype)


# -------------------------------------------------------------- multi-input

def concat_infermeta(xs: Sequence[MetaTensor], axis=0) -> MetaTensor:
    ax = _norm_axis(axis, len(xs[0].shape))
    out = list(xs[0].shape)
    out[ax] = sum(t.shape[ax] for t in xs)
    return MetaTensor(out, xs[0].dtype)


def stack_infermeta(xs: Sequence[MetaTensor], axis=0) -> MetaTensor:
    ax = _norm_axis(axis, len(xs[0].shape) + 1)
    out = list(xs[0].shape)
    out.insert(ax, len(xs))
    return MetaTensor(out, xs[0].dtype)


def split_infermeta(x: MetaTensor, num_or_sections, axis=0) \
        -> List[MetaTensor]:
    ax = _norm_axis(axis, len(x.shape))
    n = x.shape[ax]
    if isinstance(num_or_sections, int):
        if n % num_or_sections:
            raise ValueError(f"split: {n} not divisible by {num_or_sections}")
        sections = [n // num_or_sections] * num_or_sections
    else:
        sections = list(num_or_sections)
        rem = n - sum(s for s in sections if s > 0)
        sections = [rem if s in (-1,) else s for s in sections]
    outs = []
    for s in sections:
        shp = list(x.shape)
        shp[ax] = s
        outs.append(MetaTensor(shp, x.dtype))
    return outs


def where_infermeta(cond: MetaTensor, x: MetaTensor,
                    y: MetaTensor) -> MetaTensor:
    shape = broadcast_shape(broadcast_shape(cond.shape, x.shape), y.shape)
    return MetaTensor(shape, x.dtype)


def addmm_infermeta(input: MetaTensor, x: MetaTensor,
                    y: MetaTensor) -> MetaTensor:
    mm = matmul_infermeta(x, y)
    return MetaTensor(broadcast_shape(input.shape, mm.shape), mm.dtype)


# ----------------------------------------------------------------- nn ops

def _conv_out(in_size, k, stride, pad0, pad1, dilation):
    eff = (k - 1) * dilation + 1
    return (in_size + pad0 + pad1 - eff) // stride + 1


def conv2d_infermeta(x: MetaTensor, w: MetaTensor, stride=(1, 1),
                     padding=(0, 0), dilation=(1, 1),
                     data_format="NCHW") -> MetaTensor:
    """ConvInferMeta (phi/infermeta/binary.cc Conv variant), NCHW/NHWC."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    s, d = pair(stride), pair(dilation)
    p = pair(padding) if not (isinstance(padding, (list, tuple))
                              and len(padding) == 4) else None
    if p is not None:
        pads = (p[0], p[0], p[1], p[1])
    else:
        pads = tuple(padding)
    co, kh, kw = w.shape[0], w.shape[2], w.shape[3]
    if data_format == "NCHW":
        n, h, wd = x.shape[0], x.shape[2], x.shape[3]
        oh = _conv_out(h, kh, s[0], pads[0], pads[1], d[0])
        ow = _conv_out(wd, kw, s[1], pads[2], pads[3], d[1])
        return MetaTensor((n, co, oh, ow), x.dtype)
    n, h, wd = x.shape[0], x.shape[1], x.shape[2]
    oh = _conv_out(h, kh, s[0], pads[0], pads[1], d[0])
    ow = _conv_out(wd, kw, s[1], pads[2], pads[3], d[1])
    return MetaTensor((n, oh, ow, co), x.dtype)


def pool2d_infermeta(x: MetaTensor, kernel_size, stride=None, padding=0,
                     ceil_mode=False, data_format="NCHW") -> MetaTensor:
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    k = pair(kernel_size)
    s = pair(stride) if stride is not None else k
    p = pair(padding)
    rnd = math.ceil if ceil_mode else math.floor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        oh = int(rnd((h + 2 * p[0] - k[0]) / s[0])) + 1
        ow = int(rnd((w + 2 * p[1] - k[1]) / s[1])) + 1
        return MetaTensor((n, c, oh, ow), x.dtype)
    n, h, w, c = x.shape
    oh = int(rnd((h + 2 * p[0] - k[0]) / s[0])) + 1
    ow = int(rnd((w + 2 * p[1] - k[1]) / s[1])) + 1
    return MetaTensor((n, oh, ow, c), x.dtype)


def softmax_infermeta(x: MetaTensor, axis=-1) -> MetaTensor:
    _norm_axis(axis, len(x.shape))
    return MetaTensor(x.shape, x.dtype)


def cross_entropy_infermeta(logits: MetaTensor, label: MetaTensor,
                            reduction="mean") -> MetaTensor:
    if reduction in ("mean", "sum"):
        return MetaTensor((), logits.dtype)
    return MetaTensor(logits.shape[:-1], logits.dtype)


def layer_norm_infermeta(x: MetaTensor) -> MetaTensor:
    return MetaTensor(x.shape, x.dtype)


def one_hot_infermeta(x: MetaTensor, num_classes: int) -> MetaTensor:
    from . import dtype as dtype_mod
    return MetaTensor(x.shape + (num_classes,),
                      dtype_mod.get_default_dtype())


# --------------------------------------------------------------- creation

def full_infermeta(shape: Sequence[int], dtype) -> MetaTensor:
    return MetaTensor(shape, dtype)


def arange_infermeta(start, end, step, dtype) -> MetaTensor:
    n = max(0, int(np.ceil((end - start) / step)))
    return MetaTensor((n,), dtype)


def tril_triu_infermeta(x: MetaTensor, diagonal=0) -> MetaTensor:
    return MetaTensor(x.shape, x.dtype)


def eye_infermeta(num_rows, num_columns=None, dtype=np.float32) -> MetaTensor:
    return MetaTensor((num_rows, num_columns or num_rows), dtype)


# ------------------------------------------------------------ the fallback

def infer_via_eval_shape(kernel, *metas, **kwargs):
    """Generic InferMeta: XLA abstract evaluation of the kernel itself.

    The TPU-native equivalent of phi's per-op C++ shape functions — the
    compiler already knows every op's shape semantics, so the static IR
    uses this for any op without an explicit meta function above.
    """
    specs = [jax.ShapeDtypeStruct(m.shape, m.dtype) if isinstance(
        m, MetaTensor) else m for m in metas]
    out = jax.eval_shape(kernel, *specs, **kwargs)
    if isinstance(out, (tuple, list)):
        return [MetaTensor(o.shape, o.dtype) for o in out]
    return MetaTensor(out.shape, out.dtype)


# Registry: op name -> explicit meta function (static IR consults this
# first, then falls back to infer_via_eval_shape).
INFER_META = {
    "cast": cast_infermeta,
    "reshape": reshape_infermeta,
    "transpose": transpose_infermeta,
    "flatten": flatten_infermeta,
    "squeeze": squeeze_infermeta,
    "unsqueeze": unsqueeze_infermeta,
    "expand": expand_infermeta,
    "tile": tile_infermeta,
    "matmul": matmul_infermeta,
    "embedding": embedding_infermeta,
    "gather": gather_infermeta,
    "index_select": index_select_infermeta,
    "concat": concat_infermeta,
    "stack": stack_infermeta,
    "split": split_infermeta,
    "where": where_infermeta,
    "addmm": addmm_infermeta,
    "conv2d": conv2d_infermeta,
    "pool2d": pool2d_infermeta,
    "softmax": softmax_infermeta,
    "layer_norm": layer_norm_infermeta,
    "one_hot": one_hot_infermeta,
    "tril": tril_triu_infermeta,
    "triu": tril_triu_infermeta,
}
