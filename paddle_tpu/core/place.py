"""Device identity (reference: Place, phi/common/place.h:58).

A Place names a device; on TPU it resolves to a concrete jax.Device. The
reference's AllocationType enum (place.h:31) collapses to the JAX platform
string ('tpu' / 'cpu' / 'gpu'), and CustomRegisteredDeviceMap (place.h:41)
collapses to JAX's pluggable-backend registry.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base device identity: (device_type, device_id)."""

    device_type = "undefined"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    # -- resolution to a concrete jax device -------------------------------
    def get_device(self) -> jax.Device:
        devs = _devices_of(self.device_type)
        if not devs:
            raise RuntimeError(
                f"no {self.device_type!r} devices visible to JAX "
                f"(available: {[d.platform for d in jax.devices()]})"
            )
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"


class CPUPlace(Place):
    device_type = "cpu"

    def __repr__(self):
        return "Place(cpu)"


class TPUPlace(Place):
    device_type = "tpu"


class CUDAPlace(Place):  # accepted for API compatibility; maps to 'gpu'
    device_type = "gpu"


class CUDAPinnedPlace(Place):
    """Pinned-host-memory place (phi/common/place.h GPUPINNED). On TPU the
    equivalent is host memory staged for device transfer; we map it to cpu."""
    device_type = "cpu"

    def __repr__(self):
        return "Place(gpu_pinned)"


class XPUPlace(Place):  # accepted for API compatibility; maps to 'tpu'
    device_type = "tpu"


class IPUPlace(Place):
    device_type = "cpu"


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


@functools.lru_cache(maxsize=None)
def _devices_of(platform: str):
    try:
        return tuple(jax.devices(platform))
    except RuntimeError:
        return ()


def _default_platform() -> str:
    return jax.devices()[0].platform


_CURRENT_PLACE = None


def set_device(device) -> Place:
    """paddle.set_device equivalent: 'tpu', 'tpu:1', 'cpu', 'gpu:0'."""
    global _CURRENT_PLACE
    _CURRENT_PLACE = _parse_place(device)
    return _CURRENT_PLACE


def get_device() -> str:
    p = _current_place()
    return f"{p.device_type}:{p.device_id}"


def _parse_place(device) -> Place:
    if isinstance(device, Place):
        return device
    if isinstance(device, jax.Device):
        return _place_for(device.platform, device.id)
    if isinstance(device, str):
        name, _, idx = device.partition(":")
        return _place_for(name.lower(), int(idx) if idx else 0)
    raise ValueError(f"cannot interpret device spec {device!r}")


def _place_for(platform: str, idx: int) -> Place:
    if platform == "cpu":
        return CPUPlace(idx)
    if platform == "tpu":
        return TPUPlace(idx)
    if platform in ("gpu", "cuda"):
        return CUDAPlace(idx)
    return CustomPlace(platform, idx)


def _current_place() -> Place:
    global _CURRENT_PLACE
    if _CURRENT_PLACE is None:
        _CURRENT_PLACE = _place_for(_default_platform(), 0)
    return _CURRENT_PLACE


def default_device() -> jax.Device:
    return _current_place().get_device()


def is_compiled_with_tpu() -> bool:
    return bool(_devices_of("tpu"))
