"""StringTensor + string kernels.

Reference being reproduced: phi::StringTensor
(/root/reference/paddle/phi/core/string_tensor.h) — a TensorBase-family
tensor of `pstring` values with its own kernel taxonomy
(/root/reference/paddle/phi/kernels/strings/: empty, copy, lower/upper
with unicode case tables) — plus the utf-8 machinery in
kernels/strings/unicode.h.

TPU-native design: XLA has no string type, so string data is a HOST
tensor stage whose job is to feed tokenization into integer arrays that
go to the device (the reference's GPU string kernels exist for the same
boundary role). Storage is a numpy object array of python str — python
str IS a correct unicode sequence, so case mapping delegates to the
language runtime instead of hand-rolled code-point tables.
"""
from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np


class StringTensor:
    """Host tensor of unicode strings (phi::StringTensor analog)."""

    def __init__(self, data=None, dims: Sequence[int] = None,
                 name: str = None):
        if data is None:
            shape = tuple(dims or (0,))
            self._data = np.full(shape, "", dtype=object)
        else:
            arr = np.array(data, dtype=object)
            if dims is not None:
                arr = arr.reshape(tuple(dims))
            self._data = arr
        self.name = name

    # ---- TensorBase-surface parity ----------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def dims(self) -> List[int]:
        return self.shape

    def numel(self) -> int:
        return int(self._data.size)

    @property
    def dtype(self) -> str:
        return "pstring"

    @property
    def place(self) -> str:
        return "cpu"          # strings are host-resident by design

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __len__(self):
        if self._data.ndim == 0:
            raise TypeError("len() of a 0-d StringTensor")
        return self._data.shape[0]

    def __repr__(self):
        return (f"StringTensor(shape={self.shape}, "
                f"{np.array2string(self._data, threshold=8)})")

    def __eq__(self, other):
        if isinstance(other, StringTensor):
            return bool((self._data == other._data).all())
        return NotImplemented

    def copy_(self, src: "StringTensor"):
        """strings_copy kernel."""
        self._data = src._data.copy()
        return self


# ---- the strings_* kernel surface -----------------------------------

def strings_empty(shape: Sequence[int]) -> StringTensor:
    """strings_empty_kernel: an empty-string tensor of `shape`."""
    return StringTensor(dims=shape)


def _map(fn, x: StringTensor) -> StringTensor:
    out = np.empty(x._data.shape, dtype=object)
    flat_in = x._data.reshape(-1)
    flat_out = out.reshape(-1)
    for i, s in enumerate(flat_in):
        flat_out[i] = fn(s)
    return StringTensor(out)


def strings_lower(x: StringTensor, use_utf8_encoding: bool = True
                  ) -> StringTensor:
    """strings_lower_upper_kernel (lower). use_utf8_encoding=False
    restricts to ASCII case mapping (the reference's non-utf8 path)."""
    if use_utf8_encoding:
        return _map(str.lower, x)
    return _map(lambda s: "".join(
        c.lower() if c.isascii() else c for c in s), x)


def strings_upper(x: StringTensor, use_utf8_encoding: bool = True
                  ) -> StringTensor:
    if use_utf8_encoding:
        return _map(str.upper, x)
    return _map(lambda s: "".join(
        c.upper() if c.isascii() else c for c in s), x)
