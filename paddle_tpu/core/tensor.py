"""Eager Tensor.

Reference being reproduced: the public ref-counted Tensor handle
(phi/api/include/tensor.h:82) + AutogradMeta (eager/autograd_meta.h:61) +
DenseTensor meta (phi/core/dense_tensor.h:37).

TPU-native design: the storage is a jax.Array (an XLA on-device buffer —
the DenseTensor/Allocation pair collapses into it); autograd metadata lives
directly on the Python handle. Mutation (`inplace:` ops in ops.yaml) is
rebinding `_data` with a version bump — XLA buffers are immutable, so saved
backward residuals can never be corrupted by inplace ops (the reference needs
version counters to *detect* this; we keep the counter for API parity).
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .place import Place, _current_place, _parse_place

# Scalarization interceptor (the SOT guard-capture seam, installed by
# paddle_tpu.jit): fn(kind, array) -> (handled, python_value). Active
# only while a to_static probe/replay context is open; None otherwise.
_scalarize_interceptor = None


def set_scalarize_interceptor(fn):
    global _scalarize_interceptor
    prev = _scalarize_interceptor
    _scalarize_interceptor = fn
    return prev


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_grad_node", "_out_idx",
                 "name", "persistable", "_grad_hooks", "_post_acc_hooks",
                 "_version", "_sharding_hint", "__weakref__", "__dict__")

    # make Tensor win over np.ndarray in mixed dunder dispatch
    __array_priority__ = 100

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True,
                 name=None):
        dt = dtype_mod.jax_dtype(dtype)
        if isinstance(data, Tensor):
            arr = data._data
            if dt is not None and arr.dtype != dt:
                arr = arr.astype(dt)
        elif data is None:
            arr = jnp.zeros((), dt or dtype_mod.get_default_dtype())
        else:
            if isinstance(data, (float, int, bool, complex)) or (
                    isinstance(data, (list, tuple))):
                data = np.asarray(data)
            if isinstance(data, np.ndarray) and dt is None and \
                    data.dtype == np.float64:
                # match paddle.to_tensor: python floats land as default dtype
                dt = dtype_mod.get_default_dtype()
            arr = jnp.asarray(data, dtype=dt)
        if place is not None:
            arr = jax.device_put(arr, _parse_place(place).get_device())
        self._init_from_array(arr, stop_gradient, name)

    def _init_from_array(self, arr, stop_gradient=True, name=None):
        self._data = arr
        self.stop_gradient = bool(stop_gradient)
        self.grad: Optional[Tensor] = None
        self._grad_node = None
        self._out_idx = 0
        self.name = name
        self.persistable = False
        self._grad_hooks = []
        self._post_acc_hooks = []
        self._version = 0
        self._sharding_hint = None

    @classmethod
    def _wrap(cls, arr, stop_gradient=True) -> "Tensor":
        t = cls.__new__(cls)
        t._init_from_array(arr, stop_gradient)
        return t

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = property(lambda self: self._data.ndim)

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def place(self) -> Place:
        try:
            dev = next(iter(self._data.devices()))
            return _parse_place(dev)
        except Exception:  # tracer inside jit
            return _current_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def T(self):
        from paddle_tpu import ops
        return ops.manipulation.transpose(
            self, list(range(self.ndim))[::-1])

    @property
    def mT(self):
        from paddle_tpu import ops
        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return ops.manipulation.transpose(self, perm)

    @property
    def data(self):
        return self

    @data.setter
    def data(self, value):
        v = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        self._assign_array(v)

    def inplace_version(self):
        return self._version

    # ------------------------------------------------------------- transfer
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        if not args and _scalarize_interceptor is not None:
            handled, val = _scalarize_interceptor("item", self._data)
            if handled:
                return val
        arr = np.asarray(self._data)
        return arr.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def detach(self) -> "Tensor":
        return Tensor._wrap(self._data, stop_gradient=True)

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from paddle_tpu.core.dispatch import run_op
        return run_op("clone", lambda x: x + jnp.zeros((), x.dtype), self)

    def to(self, *args, **kwargs):
        """to(dtype) / to(device) / to(device, dtype)."""
        device = kwargs.get("device")
        dt = kwargs.get("dtype")
        for a in args:
            if isinstance(a, (str, Place, jax.Device)):
                try:
                    dt2 = dtype_mod.convert_dtype(a)
                    dt = dt2
                    continue
                except TypeError:
                    pass
                device = a
            else:
                dt = a
        arr = self._data
        if dt is not None:
            arr = arr.astype(dtype_mod.jax_dtype(dt))
        if device is not None:
            arr = jax.device_put(arr, _parse_place(device).get_device())
        out = Tensor._wrap(arr, self.stop_gradient)
        return out

    def cpu(self):
        return self.to(device="cpu")

    def pin_memory(self):
        return self

    def cuda(self, device_id=0):
        return self.to(device=f"gpu:{device_id}")

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph=False):
        from paddle_tpu.autograd.tape import run_backward
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        self._grad_hooks.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass
        return _Handle()

    def _register_grad_hook(self, hook):
        return self.register_hook(hook)

    def _register_backward_hook(self, hook):
        """Post-accumulation hook on a leaf (reference: accumulation node
        hooks — where the DP reducer attaches, reducer.cc:794)."""
        self._post_acc_hooks.append(hook)

    def clear_grad(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor._wrap(jnp.zeros_like(self.grad._data), True)
        else:
            self.grad = None

    def clear_gradient(self, set_to_zero=False):
        self.clear_grad(set_to_zero)

    def zero_grad(self):
        self.clear_grad()

    @property
    def grad_fn(self):
        return self._grad_node

    # ------------------------------------------------------------ mutation
    def _assign_array(self, arr):
        """Inplace rebind (the `inplace: (x -> out)` discipline, ops.yaml:16)."""
        self._data = arr
        self._version += 1
        return self

    def set_value(self, value):
        v = value._data if isinstance(value, Tensor) else \
            jnp.asarray(value, dtype=self._data.dtype)
        if tuple(v.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {v.shape} vs {self._data.shape}")
        return self._assign_array(v.astype(self._data.dtype))

    def copy_(self, other):
        return self.set_value(other)

    # ------------------------------------------------------------- dunders
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        from .flags import get_flag
        try:
            body = np.array2string(
                np.asarray(self._data),
                precision=get_flag("FLAGS_tensor_print_precision"),
                threshold=get_flag("FLAGS_tensor_print_threshold"),
                separator=", ")
        except Exception:
            body = f"<traced {self._data}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"place={getattr(self.place, 'device_type', '?')}, "
                f"stop_gradient={sg},\n       {body})")

    def __bool__(self):
        if _scalarize_interceptor is not None:
            handled, val = _scalarize_interceptor("bool", self._data)
            if handled:
                return val
        return bool(np.asarray(self._data))

    def __int__(self):
        if _scalarize_interceptor is not None:
            handled, val = _scalarize_interceptor("int", self._data)
            if handled:
                return val
        return int(np.asarray(self._data))

    def __float__(self):
        if _scalarize_interceptor is not None:
            handled, val = _scalarize_interceptor("float", self._data)
            if handled:
                return val
        return float(np.asarray(self._data))

    def __index__(self):
        if _scalarize_interceptor is not None:
            handled, val = _scalarize_interceptor("int", self._data)
            if handled:
                return val
        return int(np.asarray(self._data))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return str(self)

    def __hash__(self):
        return id(self)

    def __dlpack__(self, *a, **k):
        return self._data.__dlpack__(*a, **k)

    # arithmetic / comparison / indexing dunders are patched in by
    # paddle_tpu.ops (see ops/__init__.py: _patch_tensor_methods) so the op
    # layer stays in one place (mirrors paddle's math-op patch,
    # fluid/pybind/eager_math_op_patch.cc).


class Parameter(Tensor):
    """Trainable tensor (reference: base.framework.Parameter / EagerParamBase)."""

    def __init__(self, data=None, dtype=None, stop_gradient=False,
                 trainable=True, name=None, **kw):
        super().__init__(data, dtype=dtype, stop_gradient=stop_gradient,
                         name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False

    @classmethod
    def _wrap_param(cls, arr, trainable=True, name=None):
        p = cls.__new__(cls)
        p._init_from_array(arr, stop_gradient=not trainable, name=name)
        p.trainable = trainable
        p.persistable = True
        p.optimize_attr = {"learning_rate": 1.0}
        p.regularizer = None
        p.need_clip = True
        p.is_distributed = False
        return p

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
