"""paddle.cost_model equivalent (reference: python/paddle/cost_model —
CostModel.profile_measure + the static_op_benchmark.json table backing
the auto-parallel planner).

TPU-native: instead of a pre-measured per-op latency JSON, costs come
from (a) an analytic roofline over published TPU peak numbers
(MXU flops, HBM bandwidth, ICI bandwidth) for planning without
hardware, and (b) `profile_measure`, which times a jitted callable on
the attached device — the measured path the reference gets from its
benchmark table."""
from .cost_model import (  # noqa: F401
    CostModel, TPU_SPECS, OpCost, gpt_flops_per_token, mfu)

__all__ = ["CostModel", "TPU_SPECS", "OpCost", "gpt_flops_per_token",
           "mfu"]
