"""Analytic + measured cost model (reference:
python/paddle/cost_model/cost_model.py:33 and the planner usage in
distributed/auto_parallel/static/cost/)."""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

# Public per-chip peak specs (bf16 matmul FLOP/s, HBM B/s, ICI B/s per
# link). Sources: cloud.google.com/tpu/docs system-architecture pages.
TPU_SPECS: Dict[str, Dict[str, float]] = {
    "v4":  {"flops": 275e12, "hbm_bw": 1.2e12,  "ici_bw": 50e9},
    "v5e": {"flops": 197e12, "hbm_bw": 0.82e12, "ici_bw": 50e9},
    "v5p": {"flops": 459e12, "hbm_bw": 2.76e12, "ici_bw": 100e9},
    "v6e": {"flops": 918e12, "hbm_bw": 1.64e12, "ici_bw": 100e9},
}


def gpt_flops_per_token(cfg, seq_len: int) -> float:
    """Training FLOPs per token of a GPT-family config: 6*N for the
    parameter matmuls (fwd + bwd) + the 12*L*H*S attention term — the
    single home of the formula bench.py and the observability MFU gauge
    share. `cfg` needs vocab_size/hidden_size/max_seq_len/num_layers."""
    n = (cfg.vocab_size * cfg.hidden_size
         + cfg.max_seq_len * cfg.hidden_size
         + cfg.num_layers * (12 * cfg.hidden_size * cfg.hidden_size
                             + 13 * cfg.hidden_size)
         + 2 * cfg.hidden_size)
    return float(6 * n + 12 * cfg.num_layers * cfg.hidden_size * seq_len)


def mfu(tokens_per_s: float, flops_per_token: float,
        chip: str = "v5e") -> float:
    """Achieved model-flops utilization against one chip's bf16 peak."""
    return tokens_per_s * flops_per_token / TPU_SPECS[chip]["flops"]


@dataclass
class OpCost:
    """Cost estimate for one op (reference: auto_parallel cost items:
    comp_cost / comm_cost entries)."""
    flops: float = 0.0
    bytes_accessed: float = 0.0
    comm_bytes: float = 0.0
    time_s: float = 0.0

    def __add__(self, other: "OpCost") -> "OpCost":
        return OpCost(self.flops + other.flops,
                      self.bytes_accessed + other.bytes_accessed,
                      self.comm_bytes + other.comm_bytes,
                      self.time_s + other.time_s)


class CostModel:
    def __init__(self, chip: str = "v5p"):
        if chip not in TPU_SPECS:
            raise ValueError(f"unknown chip {chip!r}; one of "
                             f"{sorted(TPU_SPECS)}")
        self.chip = chip
        self.spec = TPU_SPECS[chip]

    # ----------------------------------------------------- analytic path
    def matmul_cost(self, m: int, n: int, k: int, dtype_bytes: int = 2,
                    batch: int = 1) -> OpCost:
        flops = 2.0 * batch * m * n * k
        byts = dtype_bytes * batch * (m * k + k * n + m * n)
        return self._finish(OpCost(flops=flops, bytes_accessed=byts))

    def elementwise_cost(self, numel: int, n_operands: int = 2,
                         dtype_bytes: int = 2) -> OpCost:
        byts = dtype_bytes * numel * (n_operands + 1)
        return self._finish(OpCost(flops=numel, bytes_accessed=byts))

    def attention_cost(self, batch: int, heads: int, seq: int,
                       head_dim: int, dtype_bytes: int = 2,
                       flash: bool = True) -> OpCost:
        flops = 4.0 * batch * heads * seq * seq * head_dim
        io = dtype_bytes * batch * heads * seq * head_dim * 4
        if not flash:                       # materialized S/P matrices
            io += dtype_bytes * batch * heads * seq * seq * 2
        return self._finish(OpCost(flops=flops, bytes_accessed=io))

    def collective_cost(self, kind: str, bytes_per_rank: float,
                        n_ranks: int) -> OpCost:
        """Ring-model cost over ICI (scaling-book formulation):
        all_reduce moves 2(n-1)/n, all_gather / reduce_scatter
        (n-1)/n, all_to_all (n-1)/n of the payload per link."""
        if n_ranks <= 1:
            return OpCost()
        factor = {"all_reduce": 2.0, "all_gather": 1.0,
                  "reduce_scatter": 1.0, "all_to_all": 1.0,
                  "ppermute": 1.0, "send_recv": 1.0}[kind]
        wire = factor * (n_ranks - 1) / n_ranks * bytes_per_rank
        c = OpCost(comm_bytes=wire)
        c.time_s = wire / self.spec["ici_bw"]
        return c

    def _finish(self, c: OpCost) -> OpCost:
        """Roofline: time = max(compute, memory) (+comm handled by
        collective_cost)."""
        c.time_s = max(c.flops / self.spec["flops"],
                       c.bytes_accessed / self.spec["hbm_bw"])
        return c

    def roofline_intensity(self) -> float:
        """FLOP/byte at the compute/memory ridge point."""
        return self.spec["flops"] / self.spec["hbm_bw"]

    # ----------------------------------------------------- measured path
    def profile_measure(self, fn, args: Sequence, steps: int = 10,
                        warmup: int = 3) -> float:
        """Wall-clock a jitted callable on the attached device
        (reference CostModel.profile_measure over a Program; here over
        a jax-compiled function). Returns seconds/step."""
        import jax
        compiled = jax.jit(fn)
        for _ in range(warmup):
            out = compiled(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = compiled(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / steps

    # ------------------------------------------------ model-level helper
    @staticmethod
    def train_flops(n_params: float, layers: int, hidden: int, seq: int,
                    batch_tokens: float) -> float:
        """fwd+bwd transformer FLOPs: 6/param/token + the attention
        quadratic term — the single home of this formula (used by
        transformer_step_cost and the distributed planner)."""
        return (6.0 * n_params + 12.0 * layers * hidden * seq) \
            * batch_tokens

    def transformer_step_cost(self, n_params: float, batch_tokens: float,
                              hidden: int, layers: int, seq: int,
                              n_chips: int = 1, dp: int = 1, tp: int = 1,
                              dtype_bytes: int = 2) -> OpCost:
        """End-to-end train-step estimate with DP grad all_reduce and TP
        activation collectives — the planner's objective function."""
        flops = self.train_flops(n_params, layers, hidden, seq,
                                 batch_tokens)
        cost = OpCost(flops=flops,
                      bytes_accessed=dtype_bytes * n_params * 3)
        cost = self._finish(cost)
        if dp > 1:
            cost = cost + self.collective_cost(
                "all_reduce", dtype_bytes * n_params / tp, dp)
        if tp > 1:
            per_layer = dtype_bytes * batch_tokens * hidden
            cost = cost + self.collective_cost(
                "all_reduce", 2 * layers * per_layer / dp, tp)
        return cost
