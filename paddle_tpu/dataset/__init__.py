"""paddle.dataset equivalent (reference: python/paddle/dataset/) —
legacy reader-style dataset loaders. The reference downloads archives;
this environment has no egress, so each loader reads a local copy when
present (same cache layout, ``~/.cache/paddle/dataset``) and otherwise
falls back to a small deterministic synthetic sample with the exact
item shapes/dtypes of the original, keeping reader-API consumers
runnable end to end."""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import image  # noqa: F401

__all__ = []
