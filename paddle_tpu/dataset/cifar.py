"""CIFAR readers (reference: python/paddle/dataset/cifar.py).
Items: (image float32[3072] in [0,1], label int)."""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from .common import DATA_HOME

_SYNTH_N = 256


def _read_batch(batch):
    data = batch[b'data'].astype(np.float32) / 255.0
    labels = batch.get(b'labels', batch.get(b'fine_labels'))
    for d, l in zip(data, labels):
        yield d, int(l)


def reader_creator(filename, sub_name):
    def reader():
        with tarfile.open(filename, mode='r') as f:
            names = [n for n in f.getnames() if sub_name in n]
            names.sort()
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding='bytes')
                for item in _read_batch(batch):
                    yield item

    return reader


def _synth_reader(seed, nclass):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            yield (rs.rand(3072).astype(np.float32),
                   int(rs.randint(nclass)))

    return reader


def _make(split, nclass):
    name = "cifar-100-python.tar.gz" if nclass == 100 else \
        "cifar-10-python.tar.gz"
    path = os.path.join(DATA_HOME, "cifar", name)
    sub = {"train10": "data_batch", "test10": "test_batch",
           "train100": "train", "test100": "test"}[f"{split}{nclass}"]
    if os.path.exists(path):
        return reader_creator(path, sub)
    return _synth_reader(0 if split == "train" else 1, nclass)


def train10():
    return _make("train", 10)


def test10():
    return _make("test", 10)


def train100():
    return _make("train", 100)


def test100():
    return _make("test", 100)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/cifar/cifar-10-python.tar.gz",
             "cifar", None)
