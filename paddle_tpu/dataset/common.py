"""Dataset cache helpers (reference: python/paddle/dataset/common.py)."""
from __future__ import annotations

import hashlib
import os
import pickle

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


must_mkdirs(DATA_HOME)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """reference common.py:74. No network egress here: returns the
    cached file if present, else raises with the expected path so the
    user can place the archive manually."""
    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(
        dirname, url.split('/')[-1] if save_name is None else save_name)
    if os.path.exists(filename) and (
            not md5sum or md5file(filename) == md5sum):
        return filename
    raise RuntimeError(
        f"dataset file not cached and this environment has no network "
        f"egress; place the file from {url} at {filename}")


def fetch_all():
    raise RuntimeError("fetch_all requires network egress; place dataset "
                       f"archives under {DATA_HOME} manually")


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Split reader output into pickled chunk files (reference
    common.py:152)."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Read one trainer's slice of chunked files (reference
    common.py:190)."""

    def reader():
        import glob
        file_list = glob.glob(files_pattern)
        file_list.sort()
        my_file_list = [f for i, f in enumerate(file_list)
                        if i % trainer_count == trainer_id]
        for fn in my_file_list:
            with open(fn, "rb") as f:
                lines = loader(f)
                for line in lines:
                    yield line

    return reader
