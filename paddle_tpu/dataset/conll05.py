"""CoNLL-2005 SRL readers (reference: python/paddle/dataset/conll05.py).
Items: 8 aligned id-sequences + label sequence."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 128
_WORDS, _LABELS = 2000, 60


def get_dict():
    word_dict = {f"w{i}": i for i in range(_WORDS)}
    verb_dict = {f"v{i}": i for i in range(100)}
    label_dict = {f"l{i}": i for i in range(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    raise RuntimeError("emb file requires network egress; place it under "
                       "~/.cache/paddle/dataset/conll05")


def _synth_reader(seed):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            n = int(rs.randint(5, 40))
            seqs = [rs.randint(0, _WORDS, n).tolist() for _ in range(6)]
            verb = rs.randint(0, 100, n).tolist()
            mark = rs.randint(0, 2, n).tolist()
            labels = rs.randint(0, _LABELS, n).tolist()
            yield tuple(seqs) + (verb, mark, labels)

    return reader


def test():
    return _synth_reader(1)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/conll05st%2Fconll05st-tests.tar.gz",
             "conll05", None)
