"""Flowers-102 readers (reference: python/paddle/dataset/flowers.py).
Items: (image float32[3,224,224], label int)."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 64


def _synth_reader(seed, use_xmap=True):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            yield (rs.rand(3, 224, 224).astype(np.float32),
                   int(rs.randint(102)))

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _synth_reader(0)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _synth_reader(1)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _synth_reader(2)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/flowers/102flowers.tgz",
             "flowers", None)
