"""Image helpers for dataset readers (reference:
python/paddle/dataset/image.py — cv2 there; numpy/PIL-free here)."""
from __future__ import annotations

import numpy as np


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    """Resize-shorter-side + center/random crop + CHW float32
    (reference image.py simple_transform)."""
    from paddle_tpu.vision import transforms as T
    im = T.resize(im, resize_size)
    if is_train:
        h, w = im.shape[:2]
        i = np.random.randint(0, h - crop_size + 1)
        j = np.random.randint(0, w - crop_size + 1)
        im = T.crop(im, i, j, crop_size, crop_size)
        if np.random.rand() < 0.5:
            im = T.hflip(im)
    else:
        im = T.center_crop(im, crop_size)
    im = np.asarray(im, np.float32)
    if im.ndim == 3:
        im = im.transpose(2, 0, 1)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean if mean.ndim == 1 else mean.reshape(-1, 1, 1)
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    from paddle_tpu.vision.datasets import DatasetFolder
    im = DatasetFolder._default_loader(filename)
    return simple_transform(np.asarray(im), resize_size, crop_size,
                            is_train, is_color, mean)
