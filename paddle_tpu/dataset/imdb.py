"""IMDB sentiment readers (reference: python/paddle/dataset/imdb.py).
Items: (word-id list, 0/1 label)."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 256
_VOCAB = 5000


def word_dict():
    return {bytes(f"w{i}", "ascii"): i for i in range(_VOCAB)}


def _synth_reader(seed):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            n = int(rs.randint(10, 200))
            yield rs.randint(0, _VOCAB, n).tolist(), int(rs.randint(2))

    return reader


def train(word_idx=None):
    return _synth_reader(0)


def test(word_idx=None):
    return _synth_reader(1)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz",
             "imdb", None)
