"""PTB language-model readers (reference: python/paddle/dataset/imikolov.py).
Items: n-gram tuples of word ids."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 512
_VOCAB = 2000


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(_VOCAB)}


def _synth_reader(seed, n, data_type):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            if data_type == DataType.NGRAM:
                yield tuple(rs.randint(0, _VOCAB, n).tolist())
            else:
                ln = int(rs.randint(5, 30))
                seq = rs.randint(0, _VOCAB, ln).tolist()
                yield seq[:-1], seq[1:]

    return reader


def train(word_idx=None, n=5, data_type=DataType.NGRAM):
    return _synth_reader(0, n, data_type)


def test(word_idx=None, n=5, data_type=DataType.NGRAM):
    return _synth_reader(1, n, data_type)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz",
             "imikolov", None)
