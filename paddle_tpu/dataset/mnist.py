"""MNIST readers (reference: python/paddle/dataset/mnist.py:42,102,129).
Items: (image float32[784] scaled to [-1,1], label int64)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .common import DATA_HOME

_SYNTH_N = 512


def reader_creator(image_filename, label_filename, buffer_size):
    def reader():
        with gzip.open(image_filename, 'rb') as imgf, \
                gzip.open(label_filename, 'rb') as lblf:
            magic, n, rows, cols = struct.unpack(">IIII", imgf.read(16))
            struct.unpack(">II", lblf.read(8))
            while True:
                buf = imgf.read(rows * cols * buffer_size)
                if not buf:
                    break
                imgs = np.frombuffer(buf, np.uint8).reshape(
                    -1, rows * cols).astype(np.float32)
                imgs = imgs / 255.0 * 2.0 - 1.0
                lbls = np.frombuffer(
                    lblf.read(len(imgs)), np.uint8).astype(np.int64)
                for im, lb in zip(imgs, lbls):
                    yield im, int(lb)

    return reader


def _synth_reader(seed):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            yield (rs.uniform(-1, 1, 784).astype(np.float32),
                   int(rs.randint(10)))

    return reader


def _files(split):
    d = os.path.join(DATA_HOME, "mnist")
    return (os.path.join(d, f"{split}-images-idx3-ubyte.gz"),
            os.path.join(d, f"{split}-labels-idx1-ubyte.gz"))


def train():
    imgs, lbls = _files("train")
    if os.path.exists(imgs) and os.path.exists(lbls):
        return reader_creator(imgs, lbls, 100)
    return _synth_reader(0)


def test():
    imgs, lbls = _files("t10k")
    if os.path.exists(imgs) and os.path.exists(lbls):
        return reader_creator(imgs, lbls, 100)
    return _synth_reader(1)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/mnist/train-images-idx3-ubyte.gz",
             "mnist", None)
