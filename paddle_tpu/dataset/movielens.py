"""MovieLens-1M readers (reference: python/paddle/dataset/movielens.py).
Items: [user_id, gender, age, job, movie_id, categories, title, score]."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 512


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, [c for c in self.categories],
                [t for t in self.title.split()]]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = age
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]


def max_movie_id():
    return 3952


def max_user_id():
    return 6040


def max_job_id():
    return 20


def movie_categories():
    return {c: i for i, c in enumerate(
        ["Action", "Adventure", "Animation", "Children's", "Comedy",
         "Crime", "Documentary", "Drama", "Fantasy", "Film-Noir",
         "Horror", "Musical", "Mystery", "Romance", "Sci-Fi",
         "Thriller", "War", "Western"])}


def user_info():
    rs = np.random.RandomState(7)
    return {i: UserInfo(i, 'M' if rs.rand() < 0.5 else 'F',
                        int(rs.randint(1, 57)), int(rs.randint(21)))
            for i in range(1, 101)}


def movie_info():
    rs = np.random.RandomState(8)
    cats = list(movie_categories())
    return {i: MovieInfo(i, [cats[rs.randint(len(cats))]], f"title {i}")
            for i in range(1, 101)}


def _synth_reader(seed):
    users, movies = user_info(), movie_info()

    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            u = users[int(rs.randint(1, 101))]
            m = movies[int(rs.randint(1, 101))]
            score = float(rs.randint(1, 6))
            yield u.value() + m.value() + [[score]]

    return reader


def train():
    return _synth_reader(0)


def test():
    return _synth_reader(1)


def get_movie_title_dict():
    return {f"title": 0}


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip",
             "movielens", None)
