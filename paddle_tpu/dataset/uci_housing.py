"""UCI housing readers (reference: python/paddle/dataset/uci_housing.py).
Items: (features float32[13], price float32[1])."""
from __future__ import annotations

import os

import numpy as np

from .common import DATA_HOME

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']

UCI_TRAIN_DATA = None
UCI_TEST_DATA = None


def feature_range(maximums, minimums):
    pass


def load_data(filename, feature_num=14, ratio=0.8):
    global UCI_TRAIN_DATA, UCI_TEST_DATA
    if UCI_TRAIN_DATA is not None and UCI_TEST_DATA is not None:
        return
    data = np.fromfile(filename, sep=' ')
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums, minimums, avgs = (data.max(axis=0), data.min(axis=0),
                                data.sum(axis=0) / data.shape[0])
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    UCI_TRAIN_DATA = data[:offset]
    UCI_TEST_DATA = data[offset:]


def _synth(seed, n=128):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 13).astype(np.float32)
    w = rs.randn(13).astype(np.float32)
    y = (x @ w + 0.1 * rs.randn(n)).astype(np.float32)
    return np.concatenate([x, y[:, None]], 1)


def _rows(split):
    path = os.path.join(DATA_HOME, "uci_housing", "housing.data")
    if os.path.exists(path):
        load_data(path)
        return UCI_TRAIN_DATA if split == "train" else UCI_TEST_DATA
    return _synth(0 if split == "train" else 1)


def train():
    def reader():
        for row in _rows("train"):
            yield row[:-1].astype(np.float32), row[-1:].astype(np.float32)

    return reader


def test():
    def reader():
        for row in _rows("test"):
            yield row[:-1].astype(np.float32), row[-1:].astype(np.float32)

    return reader


def fetch():
    from .common import download
    download("https://archive.ics.uci.edu/ml/machine-learning-databases/"
             "housing/housing.data", "uci_housing", None)
