"""VOC2012 segmentation readers (reference: python/paddle/dataset/voc2012.py).
Items: (image float32[3,H,W], seg-label int32[H,W])."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 32


def _synth_reader(seed):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            h = w = 128
            yield (rs.rand(3, h, w).astype(np.float32),
                   rs.randint(0, 21, (h, w)).astype(np.int32))

    return reader


def train():
    return _synth_reader(0)


def test():
    return _synth_reader(1)


def val():
    return _synth_reader(2)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/voc2012%2FVOCtrainval_11-May-2012.tar",
             "voc2012", None)
