"""WMT14 en-fr translation readers (reference:
python/paddle/dataset/wmt14.py). Items: (src ids, trg ids, trg-next ids)."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 256


def _synth_reader(seed, dict_size):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            ns, nt = int(rs.randint(5, 30)), int(rs.randint(5, 30))
            src = rs.randint(0, dict_size, ns).tolist()
            trg = rs.randint(0, dict_size, nt).tolist()
            yield src, trg, trg[1:] + [1]

    return reader


def train(dict_size):
    return _synth_reader(0, dict_size)


def test(dict_size):
    return _synth_reader(1, dict_size)


def get_dict(dict_size, reverse=True):
    src = {i: f"w{i}" for i in range(dict_size)}
    trg = {i: f"t{i}" for i in range(dict_size)}
    if not reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def fetch():
    from .common import download
    download("http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz", "wmt14",
             None)
