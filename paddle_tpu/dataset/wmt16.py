"""WMT16 en-de translation readers (reference:
python/paddle/dataset/wmt16.py). Items: (src ids, trg ids, trg-next ids)."""
from __future__ import annotations

import numpy as np

_SYNTH_N = 256


def _synth_reader(seed, src_dict_size, trg_dict_size):
    def reader():
        rs = np.random.RandomState(seed)
        for _ in range(_SYNTH_N):
            ns, nt = int(rs.randint(5, 30)), int(rs.randint(5, 30))
            src = rs.randint(0, src_dict_size, ns).tolist()
            trg = rs.randint(0, trg_dict_size, nt).tolist()
            yield src, trg, trg[1:] + [1]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _synth_reader(0, src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _synth_reader(1, src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _synth_reader(2, src_dict_size, trg_dict_size)


def get_dict(lang, dict_size, reverse=False):
    d = {f"{lang}{i}": i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d


def fetch():
    from .common import download
    download("http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz", "wmt16",
             None)
