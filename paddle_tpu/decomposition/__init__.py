"""paddle.decomposition equivalent (reference: python/paddle/decomposition
— decompose() rewrites composite PIR ops into the ~primitive set for
higher-order AD and the compiler).

TPU-native framing: XLA itself decomposes composite HLO into primitive
HLO, and jax.vjp/jvp already differentiate through every primitive, so
the *execution* need the reference serves is absorbed by the compiler.
What this package keeps is the API surface and an inspectable rule
registry: python decomposition rules for composite ops (softmax,
layer_norm, gelu, ...) expressed over primitive jnp ops, usable to
lower a captured program to primitives explicitly (e.g. for
quantization passes or custom-vjp analysis)."""
from .register import register_decomp, get_decomp_rule, has_decomp_rule
from . import rules  # noqa: F401  (populates the registry)
from .decomp import decompose, prim_guard, enable_prim, prim_enabled

__all__ = [
    "decompose", "register_decomp", "get_decomp_rule", "has_decomp_rule",
    "prim_guard", "enable_prim", "prim_enabled",
]
