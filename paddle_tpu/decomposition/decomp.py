"""decompose() + prim-mode switches (reference: decomposition/decomp.py:193
decompose(program, src_vars); base prim flags)."""
from __future__ import annotations

import contextlib

_prim_enabled = False


def prim_enabled():
    return _prim_enabled


def enable_prim(flag=True):
    global _prim_enabled
    _prim_enabled = bool(flag)


@contextlib.contextmanager
def prim_guard():
    """reference decomp.py:40 prim_guard."""
    prev = _prim_enabled
    enable_prim(True)
    try:
        yield
    finally:
        enable_prim(prev)


def decompose(program, src_vars=None, blacklist=frozenset(),
              whitelist=frozenset()):
    """Decompose composite ops in a captured static Program into
    primitives (reference decomp.py:193).

    On this framework the static path lowers through jax -> StableHLO,
    where XLA performs primitive decomposition as part of compilation;
    a captured Program therefore IS primitive-decomposed at the HLO
    level already. This keeps the API: it returns the program (and the
    passed vars) unchanged, after validating any white/blacklist names
    against the rule registry."""
    from .register import has_decomp_rule
    for name in whitelist:
        if not has_decomp_rule(name):
            raise ValueError(f"no decomposition rule registered for "
                             f"{name!r}")
    if src_vars is None:
        return program
    return program, src_vars
