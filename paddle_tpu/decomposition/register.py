"""Decomposition-rule registry (reference: decomposition/register.py)."""
from __future__ import annotations

_rules = {}


def register_decomp(op_name):
    """Decorator: register fn as the primitive decomposition of
    op_name."""

    def wrap(fn):
        _rules[op_name] = fn
        return fn

    return wrap


def get_decomp_rule(op_name):
    return _rules.get(op_name)


def has_decomp_rule(op_name):
    return op_name in _rules
