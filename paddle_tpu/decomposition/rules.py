"""Primitive decompositions of composite ops (reference:
decomposition/rules.py — same op list, expressed over jnp primitives
rather than C++ prim ops)."""
from __future__ import annotations

import jax.numpy as jnp

from .register import register_decomp


@register_decomp("softmax")
def softmax(x, axis=-1):
    m = jnp.max(x, axis=axis, keepdims=True)
    e = jnp.exp(x - m)
    return e / jnp.sum(e, axis=axis, keepdims=True)


@register_decomp("log_softmax")
def log_softmax(x, axis=-1):
    m = jnp.max(x, axis=axis, keepdims=True)
    s = x - m
    return s - jnp.log(jnp.sum(jnp.exp(s), axis=axis, keepdims=True))


@register_decomp("gelu")
def gelu(x, approximate=False):
    if approximate:
        c = jnp.sqrt(jnp.asarray(2.0 / jnp.pi, x.dtype))
        return 0.5 * x * (1 + jnp.tanh(c * (x + 0.044715 * x ** 3)))
    from jax.scipy.special import erf
    return 0.5 * x * (1 + erf(x / jnp.sqrt(jnp.asarray(2.0, x.dtype))))


@register_decomp("silu")
def silu(x):
    return x * (1 / (1 + jnp.exp(-x)))


@register_decomp("layer_norm")
def layer_norm(x, scale=None, bias=None, epsilon=1e-5,
               begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis if begin_norm_axis >= 0
                       else x.ndim + begin_norm_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + epsilon)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


@register_decomp("rms_norm")
def rms_norm(x, scale=None, epsilon=1e-6):
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    out = x / jnp.sqrt(var + epsilon)
    return out * scale if scale is not None else out


@register_decomp("batch_norm")
def batch_norm(x, mean, variance, scale=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    shape = [1, -1] + [1] * (x.ndim - 2) if data_format == "NCHW" \
        else [1] * (x.ndim - 1) + [-1]
    out = (x - mean.reshape(shape)) / jnp.sqrt(
        variance.reshape(shape) + epsilon)
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@register_decomp("dropout")
def dropout(x, mask, p=0.5):
    return x * mask / (1.0 - p)


@register_decomp("mean")
def mean(x, axis=None, keepdim=False):
    n = x.size if axis is None else jnp.prod(
        jnp.asarray([x.shape[a] for a in
                     (axis if isinstance(axis, (list, tuple)) else [axis])]))
    return jnp.sum(x, axis=axis, keepdims=keepdim) / n


@register_decomp("sigmoid")
def sigmoid(x):
    return 1 / (1 + jnp.exp(-x))


@register_decomp("swiglu")
def swiglu(x, y=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return x * (1 / (1 + jnp.exp(-x))) * y


@register_decomp("squared_l2_norm")
def squared_l2_norm(x):
    return jnp.sum(x * x)
