"""paddle.device equivalent: device selection + memory stats
(reference: python/paddle/device + phi/core/memory/stats.cc surfaced as
paddle.device.cuda.max_memory_allocated etc.)."""
from __future__ import annotations

import jax

from paddle_tpu.core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CustomPlace, Place, TPUPlace, get_device,
    set_device, is_compiled_with_tpu,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform not in ("cpu", "gpu", "tpu")]


def device_count(device_type=None):
    if device_type is None:
        return len(jax.devices())
    try:
        return len(jax.devices(device_type))
    except RuntimeError:
        return 0


def synchronize(device=None):
    (jax.device_put(0) + 0).block_until_ready()


def _mem_stats(device_id=0):
    try:
        devs = jax.devices()
        d = devs[device_id % len(devs)]
        return d.memory_stats() or {}
    except Exception:
        return {}


def _device_id(device) -> int:
    """Accept int, 'tpu:3'/'gpu:3' strings, Place, or jax.Device."""
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    if isinstance(device, str):
        return int(device.split(":")[1]) if ":" in device else 0
    return int(getattr(device, "id", getattr(device, "device_id", 0)))


def max_memory_allocated(device=None):
    return _mem_stats(_device_id(device)).get("peak_bytes_in_use", 0)


def max_memory_reserved(device=None):
    return _mem_stats(_device_id(device)).get(
        "peak_pool_bytes", max_memory_allocated(device))


def memory_allocated(device=None):
    return _mem_stats(_device_id(device)).get("bytes_in_use", 0)


def memory_reserved(device=None):
    return _mem_stats(_device_id(device)).get(
        "pool_bytes", memory_allocated(device))


def dump_memory_stats(path=None, device=None):
    """Write the device memory stats as JSON to `path` (or
    FLAGS_memory_stats_dump_path) — the reference's memory-stats dump
    debugging surface. Returns the dict written."""
    import json
    from paddle_tpu.core.flags import get_flag
    path = path or get_flag("FLAGS_memory_stats_dump_path")
    stats = {
        "bytes_in_use": memory_allocated(device),
        "peak_bytes_in_use": max_memory_allocated(device),
        "pool_bytes": memory_reserved(device),
        "peak_pool_bytes": max_memory_reserved(device),
        "raw": {k: v for k, v in _mem_stats(
            _device_id(device)).items()
            if isinstance(v, (int, float, str))},
    }
    if path:
        with open(path, "w") as f:
            json.dump(stats, f, indent=1)
    return stats


class cuda:
    """Namespace parity for paddle.device.cuda (maps to the active
    accelerator's stats)."""

    device_count = staticmethod(lambda: device_count("gpu"))
    synchronize = staticmethod(synchronize)
    max_memory_allocated = staticmethod(max_memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_allocated = staticmethod(memory_allocated)
    memory_reserved = staticmethod(memory_reserved)

    @staticmethod
    def empty_cache():
        import gc
        gc.collect()


class tpu:
    device_count = staticmethod(lambda: device_count("tpu"))
    synchronize = staticmethod(synchronize)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_allocated = staticmethod(memory_allocated)


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type):
    try:
        return bool(jax.devices(device_type))
    except RuntimeError:
        return False
