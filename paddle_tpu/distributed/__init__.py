"""paddle.distributed equivalent — TPU-native SPMD over jax.sharding.

Map from the reference stack (SURVEY §2.6/2.7):
- ProcessGroup/NCCL comms → XLA collectives over ICI (collective.ops) +
  eager parity wrappers (collective.*)
- TCPStore bootstrap → jax.distributed / TPU coordination service (env)
- HybridCommunicateGroup topology → named-axis jax Mesh (fleet.topology)
- DistTensor/ProcessMesh/reshard → NamedSharding + device_put (api, mesh)
- fleet DP/TP/PP/sharding wrappers → sharding annotations + GSPMD
"""
from .env import (  # noqa: F401
    Group, ParallelEnv, barrier, destroy_process_group, get_group,
    get_rank, get_world_size, init_parallel_env, is_initialized, new_group,
)
from .collective import (  # noqa: F401
    P2POp, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, batch_isend_irecv, broadcast, broadcast_object_list,
    irecv, isend, ops, recv, reduce, reduce_scatter, scatter, send, wait,
)
from .mesh import (  # noqa: F401
    Partial, Placement, ProcessMesh, ReduceType, Replicate, Shard,
    auto_mesh, get_mesh, set_mesh,
)
from .api import (  # noqa: F401
    ShardingStage1, ShardingStage2, ShardingStage3, dtensor_from_fn,
    dtensor_from_local, reshard, shard_layer, shard_optimizer, shard_tensor,
)
from .parallel import DataParallel  # noqa: F401
from paddle_tpu.native import TCPStore  # noqa: F401
from . import sharding  # noqa: F401
from .sharding import (  # noqa: F401
    DygraphShardingOptimizer, GroupShardedScaler, GroupShardedStage2,
    GroupShardedStage3, group_sharded_parallel, save_group_sharded_model,
)
from . import fleet  # noqa: F401
from .fleet.recompute import recompute  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference paddle.distributed.spawn (spawn.py:463). On TPU a single
    controller drives all local chips, so spawn degenerates to calling
    func once (rank 0); multi-host launch uses paddle_tpu.distributed.launch
    with one process per host."""
    func(*args)


def get_backend():
    return "xla"
