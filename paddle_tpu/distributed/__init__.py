"""paddle.distributed equivalent — TPU-native SPMD over jax.sharding.

Map from the reference stack (SURVEY §2.6/2.7):
- ProcessGroup/NCCL comms → XLA collectives over ICI (collective.ops) +
  eager parity wrappers (collective.*)
- TCPStore bootstrap → jax.distributed / TPU coordination service (env)
- HybridCommunicateGroup topology → named-axis jax Mesh (fleet.topology)
- DistTensor/ProcessMesh/reshard → NamedSharding + device_put (api, mesh)
- fleet DP/TP/PP/sharding wrappers → sharding annotations + GSPMD
"""
from .env import (  # noqa: F401
    Group, ParallelEnv, barrier, destroy_process_group, get_group,
    get_rank, get_world_size, init_parallel_env, is_initialized, new_group,
)
from .collective import (  # noqa: F401
    P2POp, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, batch_isend_irecv, broadcast, broadcast_object_list,
    irecv, isend, ops, recv, reduce, reduce_scatter, scatter, send, wait,
)
from .mesh import (  # noqa: F401
    Partial, Placement, ProcessMesh, ReduceType, Replicate, Shard,
    auto_mesh, get_mesh, set_mesh,
)
from .api import (  # noqa: F401
    ShardingStage1, ShardingStage2, ShardingStage3, dtensor_from_fn,
    dtensor_from_local, reshard, shard_layer, shard_optimizer, shard_tensor,
)
from .parallel import DataParallel  # noqa: F401
from paddle_tpu.native import TCPStore  # noqa: F401
from . import sharding  # noqa: F401
from .sharding import (  # noqa: F401
    DygraphShardingOptimizer, GroupShardedScaler, GroupShardedStage2,
    GroupShardedStage3, group_sharded_parallel, save_group_sharded_model,
)
from . import fleet  # noqa: F401
from .fleet.recompute import recompute  # noqa: F401


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference paddle.distributed.spawn (spawn.py:463). On TPU a single
    controller drives all local chips, so spawn degenerates to calling
    func once (rank 0); multi-host launch uses paddle_tpu.distributed.launch
    with one process per host."""
    func(*args)


def get_backend():
    return "xla"


# ---------------------------------------------------------------------
# remaining paddle.distributed surface (reference:
# python/paddle/distributed/__init__.py __all__)
# ---------------------------------------------------------------------
from . import checkpoint  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from . import launch  # noqa: F401
from . import rpc  # noqa: F401
from . import communication  # noqa: F401
from .communication import stream  # noqa: F401
from . import ps  # noqa: F401
from . import fleet_executor  # noqa: F401
from .collective import gather, scatter_object_list  # noqa: F401
from .api import (  # noqa: F401
    shard_dataloader, shard_scaler, to_static, unshard_dtensor, Strategy,
    DistModel, DistAttr,
)


class ParallelMode:
    """reference base/topology.py ParallelMode constants."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


def is_available():
    """reference distributed.is_available: collective support present."""
    return True


#: layers built by split(), keyed by call site — re-invoking split with the
#: same key reuses the SAME parameters (deterministic + trainable); the
#: layers (and their parameters) are reachable here for optimizers.
_split_layers = {}


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference paddle.distributed.split: build a row/column-parallel
    linear/embedding across the model-parallel group (the manual-TP
    entry point; maps to fleet mp layers here). The constructed layer is
    cached by (name, operation, size, axis) so repeated forward calls
    share one set of parameters; pass distinct ``name``s for distinct
    layers and collect parameters via
    ``paddle_tpu.distributed._split_layers[key].parameters()``."""
    from .fleet.mp_layers import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)
    key = (name, operation, tuple(size), axis, num_partitions)
    layer = _split_layers.get(key)
    if layer is None:
        if operation == "linear":
            if axis == 1:
                layer = ColumnParallelLinear(
                    size[0], size[1], weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    gather_output=gather_out)
            else:
                layer = RowParallelLinear(
                    size[0], size[1], weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    input_is_parallel=False)
        elif operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr)
        else:
            raise ValueError(f"unknown split operation {operation!r}")
        _split_layers[key] = layer
    return layer(x)


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference gloo_* CPU-barrier helpers: the coordination service
    covers this on TPU; provided for API parity."""
    init_parallel_env()


def gloo_barrier():
    barrier()


def gloo_release():
    pass


# --- PS-style dataset APIs (reference fluid DataFeed/Dataset shells;
# the C++ pipeline they front is replaced by paddle_tpu.io readers) ---

class QueueDataset:
    def __init__(self):
        self._files = []
        self.proto_desc = type("D", (), {"pipe_command": "cat"})()

    def set_filelist(self, files):
        self._files = list(files)

    def set_use_var(self, vars_):
        self._vars = vars_

    def set_batch_size(self, bs):
        self._bs = bs


class InMemoryDataset(QueueDataset):
    """reference InMemoryDataset over the C++ MultiSlot DataFeed
    (fluid/framework/data_feed.cc): when slots are configured via
    set_use_var, load_into_memory parses files with the native
    multi-threaded parser (native/src/datafeed.cc) into per-slot
    ragged arrays; otherwise falls back to raw lines."""

    def load_into_memory(self):
        slots = getattr(self, "_vars", None)
        if slots:
            from paddle_tpu import native
            import numpy as np
            is_float = [("float" in str(getattr(v, "dtype", "int64")))
                        for v in slots]
            merged = None
            for f in self._files:
                parsed = native.parse_multislot_file(f, is_float)
                if parsed is None:      # no native lib: python parse
                    parsed = self._py_parse(f, is_float)
                if merged is None:
                    merged = [[v, o] for v, o in parsed]
                else:
                    for s, (v, o) in enumerate(parsed):
                        mv, mo = merged[s]
                        merged[s] = [np.concatenate([mv, v]),
                                     np.concatenate(
                                         [mo, o[1:] + mo[-1]])]
            self._slot_data = [(v, o) for v, o in (merged or [])]
            self._data = []
            return
        self._data = []
        for f in self._files:
            with open(f) as fh:
                self._data += fh.readlines()

    @staticmethod
    def _py_parse(path, is_float):
        import numpy as np
        n = len(is_float)
        vals = [[] for _ in range(n)]
        offs = [[0] for _ in range(n)]
        with open(path) as fh:
            for line in fh:
                toks = line.split()
                i = 0
                row = [[] for _ in range(n)]
                ok = True
                for s in range(n):
                    if i >= len(toks):
                        ok = False
                        break
                    cnt = int(toks[i]); i += 1
                    row[s] = toks[i:i + cnt]
                    i += cnt
                if not ok:
                    continue
                for s in range(n):
                    conv = float if is_float[s] else int
                    vals[s] += [conv(t) for t in row[s]]
                    offs[s].append(offs[s][-1] + len(row[s]))
        return [(np.asarray(vals[s], np.float32 if is_float[s]
                            else np.int64),
                 np.asarray(offs[s], np.int64)) for s in range(n)]

    def get_memory_data_size(self):
        if getattr(self, "_slot_data", None):
            return int(self._slot_data[0][1].shape[0] - 1)
        return len(getattr(self, "_data", []))

    def slot_arrays(self):
        """Per-slot (values, offsets) ragged arrays (native layout)."""
        return getattr(self, "_slot_data", [])

    def batch_generator(self, batch_size=None, pad_value=0):
        """Yield per-slot dense [b, max_len] batches (the feed the PS
        trainer consumes)."""
        import numpy as np
        from paddle_tpu.core.tensor import Tensor
        bs = batch_size or getattr(self, "_bs", 32)
        data = getattr(self, "_slot_data", [])
        if not data:
            return
        rows = data[0][1].shape[0] - 1
        for start in range(0, rows, bs):
            stop = min(start + bs, rows)
            batch = []
            for vals, offs in data:
                seqs = [vals[offs[i]:offs[i + 1]]
                        for i in range(start, stop)]
                ml = max((len(s) for s in seqs), default=1) or 1
                dense = np.full((len(seqs), ml), pad_value,
                                vals.dtype)
                for j, s in enumerate(seqs):
                    dense[j, :len(s)] = s
                batch.append(Tensor(dense))
            yield batch

    def local_shuffle(self):
        import random
        random.shuffle(getattr(self, "_data", []))

    def release_memory(self):
        self._data = []
        self._slot_data = []


class ProbabilityEntry:
    def __init__(self, probability):
        self.probability = probability


class CountFilterEntry:
    def __init__(self, count_filter):
        self.count_filter = count_filter


class ShowClickEntry:
    def __init__(self, show_name, click_name):
        self.show_name, self.click_name = show_name, click_name


from . import io  # noqa: E402,F401
