"""Auto-parallel (semi-auto) API.

Reference: shard_tensor (distributed/auto_parallel/api.py:181),
reshard (:703), shard_optimizer (:1512), shard_layer, dtensor_from_fn,
DistTensor (phi/core/distributed/auto_parallel/dist_tensor.h:39).

TPU-native: a "DistTensor" is a Tensor whose jax.Array carries a
NamedSharding. The reference's per-op InferSpmd + reshard machinery
(dist_api_gen.py:76,:106) is XLA GSPMD: eager ops on sharded arrays and
jit'd programs both get partitioning + collectives from the compiler.
reshard() is jax.device_put to a new NamedSharding (compiled to
collective-permute / all-gather / dynamic-slice as needed).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Parameter, Tensor
from .mesh import (Partial, Placement, ProcessMesh, Replicate, Shard,
                   get_mesh, placements_to_spec, spec_to_placements)


def _named_sharding(mesh: ProcessMesh, placements, ndim) -> NamedSharding:
    spec = placements_to_spec(placements, mesh, ndim)
    return NamedSharding(mesh.jax_mesh, spec)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Place a tensor onto a mesh with per-axis placements
    (reference api.py:181)."""
    if not isinstance(data, Tensor):
        data = Tensor(data, dtype=dtype)
    for pl in placements:
        if isinstance(pl, Partial):
            raise ValueError(
                "shard_tensor cannot materialize Partial placement; Partial "
                "arises only as an op-output state and is reduced by "
                "reshard()")
    ns = _named_sharding(mesh, placements, data.ndim)
    if isinstance(data, (Parameter,)):
        data._assign_array(jax.device_put(data._data, ns))
        out = data
    elif not data.stop_gradient:
        # keep the autograd link: sharding is identity w.r.t. values,
        # so the tape records it like any other op
        from paddle_tpu.core.dispatch import run_op
        out = run_op("shard_tensor", lambda a: jax.device_put(a, ns),
                     data, amp=False)
        if stop_gradient is not None:
            out.stop_gradient = stop_gradient
    else:
        out = Tensor._wrap(jax.device_put(data._data, ns),
                           data.stop_gradient
                           if stop_gradient is None else stop_gradient)
    out._sharding_hint = ns
    return out


def reshard(x: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """Change placements (reference api.py:703; the R/S/P reshard-function
    lattice collapses into one device_put — XLA picks the collective).
    Routed through run_op so it sits on the autograd tape (the reference
    reshard has a backward; grad moves back as the transpose resharding)."""
    ns = _named_sharding(mesh, placements, x.ndim)
    from paddle_tpu.core.dispatch import run_op
    out = run_op("reshard", lambda a: jax.device_put(a, ns), x,
                 amp=False)
    out._sharding_hint = ns
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def dtensor_from_local(local_tensor, mesh, placements):
    # single-controller: the "local" tensor is the global view already
    return shard_tensor(local_tensor, mesh, placements)


# ---- introspection (DistTensor attribute parity) --------------------------
def _tensor_process_mesh(self):
    sh = getattr(self._data, "sharding", None)
    if isinstance(sh, NamedSharding):
        return ProcessMesh(mesh=sh.mesh)
    return None


def _tensor_placements(self):
    sh = getattr(self._data, "sharding", None)
    if isinstance(sh, NamedSharding):
        mesh = ProcessMesh(mesh=sh.mesh)
        return spec_to_placements(sh.spec, mesh, self.ndim)
    return None


def _tensor_is_dist(self):
    sh = getattr(self._data, "sharding", None)
    return isinstance(sh, NamedSharding) and \
        np.prod(list(sh.mesh.shape.values())) > 1


Tensor.process_mesh = property(_tensor_process_mesh)
Tensor.placements = property(_tensor_placements)
Tensor.is_dist = _tensor_is_dist


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard every parameter of a layer (reference api.py shard_layer)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in list(sublayer._parameters.items()):
                if p is not None:
                    shard_tensor(p, mesh,
                                 [Replicate()] * len(mesh.dim_names))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inp, out: output_fn(out, process_mesh))
    return layer


class _ShardOptimizer:
    """Optimizer wrapper sharding the accumulators like the params
    (reference shard_optimizer api.py:1512 — ZeRO via placement)."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner._create_accumulators()
        # co-locate accumulators with their parameters
        for name, d in self._inner._accumulators.items():
            for key, acc in d.items():
                p = next((p for p in self._inner._parameter_list
                          if id(p) == key), None)
                if p is None:
                    continue
                psh = getattr(p._data, "sharding", None)
                if isinstance(psh, NamedSharding) and \
                        acc._data.shape == p._data.shape:
                    ash = getattr(acc._data, "sharding", None)
                    if ash != psh:
                        acc._data = jax.device_put(acc._data, psh)
        if self._shard_fn is not None:
            for name, d in self._inner._accumulators.items():
                for key, acc in d.items():
                    p = next((p for p in self._inner._parameter_list
                              if id(p) == key), None)
                    if p is not None:
                        self._shard_fn(name, p, acc)
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad


def shard_optimizer(optimizer, shard_fn=None):
    return _ShardOptimizer(optimizer, shard_fn)


class ShardingStage1:
    """Marker shard_fns for shard_optimizer (reference api.py
    ShardingStage1/2/3): accumulators sharded along `shard_axis` of the
    sharding mesh dim."""

    def __init__(self, axis_name="dp", mesh=None):
        self.axis_name = axis_name
        self.mesh = mesh

    def __call__(self, acc_name, param, acc):
        mesh = self.mesh or get_mesh()
        if mesh is None or acc._data.ndim == 0:
            return
        # shard the largest dim of the accumulator across the dp axis
        dim = int(np.argmax(acc._data.shape))
        if acc._data.shape[dim] % mesh.get_dim_size(self.axis_name) != 0:
            return
        spec = [None] * acc._data.ndim
        spec[dim] = self.axis_name
        acc._data = jax.device_put(
            acc._data, NamedSharding(mesh.jax_mesh, PartitionSpec(*spec)))


ShardingStage2 = ShardingStage1  # grads live in-trace; stage2==stage1 here


class ShardingStage3(ShardingStage1):
    """Parameters themselves sharded (ZeRO-3): apply to params too."""

    def __call__(self, acc_name, param, acc):
        mesh = self.mesh or get_mesh()
        if mesh is None:
            return
        super().__call__(acc_name, param, acc)
        if param._data.ndim == 0:
            return
        dim = int(np.argmax(param._data.shape))
        if param._data.shape[dim] % mesh.get_dim_size(self.axis_name) != 0:
            return
        spec = [None] * param._data.ndim
        spec[dim] = self.axis_name
        param._assign_array(jax.device_put(
            param._data, NamedSharding(mesh.jax_mesh, PartitionSpec(*spec))))


def unshard_dtensor(dist_tensor):
    """Gather a sharded tensor into a replicated dense tensor (reference
    auto_parallel/api.py unshard_dtensor)."""
    import jax
    arr = dist_tensor._data
    if hasattr(arr, "sharding"):
        arr = jax.device_get(arr)
        import jax.numpy as jnp
        arr = jnp.asarray(np.asarray(arr))
    out = Tensor._wrap(arr, dist_tensor.stop_gradient)
    return out


def shard_dataloader(dataloader, meshes, shard_dims=None, is_dataset=False,
                     input_keys=None):
    """reference shard_dataloader (auto_parallel/api.py:3016): yield
    batches with their arrays placed/sharded on the mesh. On a
    single-controller TPU runtime the sharding happens on first use inside
    jit; we annotate eagerly with shard_tensor for parity."""
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes

    class _ShardedLoader:
        def __init__(self, dl):
            self._dl = dl

        def __len__(self):
            return len(self._dl)

        def __iter__(self):
            from .mesh import Shard, Replicate
            # shard_dims names the MESH axis (by name or index) carrying
            # the batch split; placement index i maps to mesh axis i.
            names = list(getattr(mesh, "dim_names", []) or [])
            if isinstance(shard_dims, str):
                axis = names.index(shard_dims)
            elif shard_dims is not None:
                axis = int(shard_dims)
            else:
                axis = None
            n_axes = len(names) if names else (axis + 1 if axis is not None
                                               else 0)
            for batch in self._dl:
                if axis is None:
                    yield batch
                    continue
                def place(t):
                    if not isinstance(t, Tensor):
                        return t
                    pl = [Replicate()] * max(n_axes, axis + 1)
                    pl[axis] = Shard(0)
                    return shard_tensor(t, mesh, pl)
                if isinstance(batch, (list, tuple)):
                    yield type(batch)(place(b) for b in batch)
                else:
                    yield place(batch)
    return _ShardedLoader(dataloader)


def shard_scaler(scaler):
    """reference shard_scaler: make GradScaler found_inf reduction span
    the mesh. XLA jit computes found_inf globally already — returned
    unchanged."""
    return scaler


class Strategy:
    """reference distributed.Strategy (auto_parallel/strategy.py): typed
    config bundle for to_static/DistModel."""

    class _Sub:
        def __init__(self, defaults, overrides):
            self.__dict__.update(defaults)
            self.__dict__.update(overrides)

    def __init__(self, config=None):
        cfg = config or {}
        self.sharding = Strategy._Sub(
            dict(enable=False, degree=1, stage=1), cfg.get("sharding", {}))
        self.fused_passes = Strategy._Sub(
            dict(enable=False, fused_passes_list=[]),
            cfg.get("fused_passes", {}))
        self.gradient_merge = Strategy._Sub(
            dict(enable=False, k_steps=1), cfg.get("gradient_merge", {}))
        self.pipeline = Strategy._Sub(
            dict(enable=False, schedule_mode="1F1B", micro_batch_size=1,
                 accumulate_steps=1), cfg.get("pipeline", {}))
        self.amp = Strategy._Sub(
            dict(enable=False, dtype="float16", level="O1"),
            cfg.get("amp", {}))


class DistModel:
    """reference DistModel (auto_parallel/api.py): the to_static product —
    a train/eval/predict callable over the sharded program. Here the
    compiled artifact is a jitted step function per mode."""

    def __init__(self, layer, loader, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self.network = layer
        self._loader = loader
        self._loss = loss
        self._opt = optimizer
        self._strategy = strategy or Strategy()
        self._mode = "train"
        import paddle_tpu as paddle
        self._jit_train = None
        self._jit_eval = None

    def train(self):
        self._mode = "train"
        self.network.train()

    def eval(self):
        self._mode = "eval"
        self.network.eval()

    def predict(self):
        self._mode = "predict"
        self.network.eval()

    def __call__(self, *inputs):
        import paddle_tpu as paddle
        if self._mode == "predict" or self._loss is None:
            return self.network(*inputs)
        *feats, label = inputs
        out = self.network(*feats)
        loss = self._loss(out, label)
        if self._mode == "train" and self._opt is not None:
            loss.backward()
            self._opt.step()
            self._opt.clear_grad()
        return loss

    def state_dict(self, mode="all"):
        return self.network.state_dict()

    def dist_main_program(self, mode=None):
        return None


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None):
    """reference distributed.to_static (auto_parallel/api.py:2510):
    wrap a dygraph layer + loader + loss + optimizer into a DistModel."""
    return DistModel(layer, loader, loss, optimizer, strategy)


class DistAttr:
    """Legacy TensorDistAttr surface (reference
    base/dist_attr.py DistAttr): (mesh, sharding_specs) pair."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs
