"""paddle.distributed.auto_parallel / fleet.auto namespace.

Reference layout (python/paddle/distributed/auto_parallel/): dygraph
semi-auto API (shard_tensor/reshard/ProcessMesh — here in
distributed/api.py over GSPMD) + the static side (engine.py Engine,
completion.py, planner_v2.py, partitioner.py).

TPU-native mapping: completion (dist-attr propagation across the
graph) and the partitioner (per-rank program split) ARE GSPMD — jax
propagates shardings and partitions the XLA program; the planner is
distributed/planner.py (calibrated cost-model search); the Engine here
ties them into the reference's fit/evaluate/predict/cost surface.
"""
from paddle_tpu.distributed.api import (DistAttr, DistModel, Strategy,
                                        dtensor_from_fn, reshard,
                                        shard_dataloader, shard_layer,
                                        shard_optimizer, shard_scaler,
                                        shard_tensor, to_static)
from paddle_tpu.distributed.mesh import (Partial, Placement,
                                         ProcessMesh, Replicate, Shard)
from paddle_tpu.distributed.planner import (ModelSpec, PlanCandidate,
                                            Planner)

from .engine import Engine  # noqa: E402

__all__ = [
    "Engine", "Strategy", "DistModel", "DistAttr", "to_static",
    "shard_tensor", "shard_layer", "shard_optimizer", "shard_dataloader",
    "shard_scaler", "reshard", "dtensor_from_fn",
    "ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
    "Planner", "ModelSpec", "PlanCandidate",
]
