"""Auto-parallel Engine (reference: distributed/auto_parallel/static/
engine.py:98 — Engine(model, loss, optimizer, metrics, strategy) with
fit/evaluate/predict/cost over an automatically planned distributed
program).

TPU-native decomposition of the reference pipeline:
  completion  -> GSPMD sharding propagation (jax inserts collectives)
  partitioner -> the XLA SPMD partitioner (per-device program split)
  planner_v2  -> distributed/planner.py (calibrated cost-model search)
  engine      -> this class: plans a parallel config for the attached
                 devices, builds the mesh, shards the data stream, and
                 compiles one train/eval step (jit.to_static threads
                 model+optimizer state functionally)

Plan families (round 3 — the partitioner generalizes tp/pp to
arbitrary models, VERDICT r2 item 3):
  dp x ZeRO : any model — batch sharded over the mesh, GSPMD completes.
  + tp      : any model — Linear/Embedding params auto-annotated over
              the mp axis (partitioner.annotate_tp); GSPMD propagates
              and inserts the collectives.
  + pp      : models with a homogeneous LayerList/Sequential block
              chain (the reference's PipelineLayer requirement):
              blocks are stacked onto the compiled 1F1B, with the
              model's own forward cut into prologue/epilogue by block
              shimming (partitioner.PipelinePartition).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.planner import ModelSpec, Planner


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None,
                 metrics=None, strategy=None, chip: str = "v5e"):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) \
            else ([metrics] if metrics is not None else [])
        self.strategy = strategy
        self._chip = chip
        self._devices = None
        self._mesh = None
        self._plan = None
        self._step = None
        self._eval_step = None
        self.history = []

    # ------------------------------------------------------------ plan
    def _pipeline_blocks(self):
        if not hasattr(self, "_blocks_cache"):
            from .partitioner import find_pipeline_blocks
            self._blocks_cache = find_pipeline_blocks(self.model)
        return self._blocks_cache

    def _model_spec(self) -> ModelSpec:
        n = sum(int(np.prod(p.shape))
                for _, p in self.model.named_parameters())
        blocks = self._pipeline_blocks()
        if blocks:
            # geometry from the block chain: layers = chain length,
            # hidden from the widest square-ish weight
            hidden = max((min(p.shape) for _, p in
                          blocks[0].named_parameters()
                          if len(p.shape) == 2), default=1)
            return ModelSpec(float(n), layers=len(blocks),
                             hidden=int(hidden), heads=max(1,
                             int(hidden) // 64), seq=128, vocab=1)
        return ModelSpec(float(n), layers=1, hidden=1, heads=1, seq=1,
                         vocab=1)

    def plan(self, n_chips: Optional[int] = None, global_batch: int = 32,
             top_k: int = 5):
        """Ranked parallel plans for this model on n_chips (reference
        planner_v2 through the Engine). Models with a pipeline block
        chain search the FULL (dp, tp, pp, zero) family; block-less
        models restrict to dp x ZeRO<=1 (pp needs block structure; tp
        still applies via prepare(plan=...) overrides)."""
        n = n_chips or len(jax.devices())
        # ZeRO stays capped at <=1: prepare() implements dp-replicated
        # optimizer state only, so costing zero>=2 plans would promise
        # memory the executor does not deliver. Block-chain models
        # widen the SEARCH to the tp/pp families the partitioner can
        # now execute.
        planner = Planner(self._chip, zero_stages=(0, 1))
        plans = planner.plan(self._model_spec(), n, global_batch,
                             top_k=max(top_k, 8))
        if not self._pipeline_blocks():
            # pp needs block structure this model lacks — filter those
            # plans out rather than rank the unexecutable
            plans = [p for p in plans if p.pp == 1] or plans[:1]
        return plans[:top_k]

    def cost(self, n_chips: Optional[int] = None, global_batch: int = 32):
        """Estimated (step_seconds, per_chip_memory_bytes) of the best
        plan — the reference Engine.cost surface."""
        best = self.plan(n_chips, global_batch)[0]
        return best.est_step_s, best.est_mem_bytes

    # --------------------------------------------------------- prepare
    def prepare(self, n_chips: Optional[int] = None,
                global_batch: int = 32, plan=None,
                zero_bubble=False):
        """zero_bubble compiles pp>1 plans onto a zero-bubble
        dx/dW-split ring instead of 1F1B when the plan's stage bodies
        are collective-free (tp==1); ignored otherwise. True selects
        ZBH1; the string "zbvpp" selects the two-chunk V-placement
        schedule (needs blocks % 2*pp == 0). Note: the generic
        partitioner keeps the tp==1 gate because arbitrary user models
        get GSPMD-auto tp (annotate_tp), whose collectives deadlock
        inside the cond-gated phases; the HYBRID engine
        (models/gpt_hybrid.py + planner.to_parallel_config) composes
        zero-bubble with tp>1 via its manual-tp stage body
        (models/gpt_manual_tp.py)."""
        self._zero_bubble = zero_bubble
        import paddle_tpu as paddle

        self._devices = jax.devices()[:n_chips] if n_chips else \
            jax.devices()
        best = plan if plan is not None else \
            self.plan(len(self._devices), global_batch)[0]
        self._plan = best
        if best.tp > 1 or best.pp > 1:
            return self._prepare_tp_pp(best, global_batch)
        self._mesh = Mesh(np.asarray(self._devices[:best.dp]), ("dp",))

        def train_step(xb, yb):
            out = self.model(xb)
            loss = self.loss(out, yb)
            loss.backward()
            self.optimizer.step()
            self.optimizer.clear_grad()
            return loss

        def eval_step(xb, yb):
            out = self.model(xb)
            return self.loss(out, yb)

        self._step = paddle.jit.to_static(
            train_step, objs=[self.model, self.optimizer])
        self._eval_step = paddle.jit.to_static(eval_step,
                                               objs=[self.model])
        return self

    def _prepare_tp_pp(self, best, global_batch):
        """Impose a tp/pp plan on the (unmodified) model via the
        partitioner (reference static/partitioner.py role)."""
        import paddle_tpu as paddle
        from .partitioner import PipelinePartition, annotate_tp
        need = best.dp * best.pp * best.tp
        if need > len(self._devices):
            raise ValueError(f"plan {best.short()} needs {need} "
                             f"devices, have {len(self._devices)}")
        self._mesh = Mesh(
            np.asarray(self._devices[:need]).reshape(
                best.dp, best.pp, best.tp), ("dp", "pp", "mp"))
        if best.tp > 1:
            annotate_tp(self.model, self._mesh, "mp")
        if best.pp > 1:
            blocks = self._pipeline_blocks()
            if not blocks:
                raise NotImplementedError(
                    f"plan {best.short()} needs a homogeneous "
                    "LayerList/Sequential block chain for pipeline "
                    "partitioning (the reference PipelineLayer "
                    "contract); this model has none")
            # honor an explicitly planned microbatch count;
            # microbatches=1 (the dataclass default) means "unset" and
            # gets the bubble-friendly 2*pp
            mbs = best.microbatches if best.microbatches > 1 \
                else 2 * best.pp
            zb = getattr(self, "_zero_bubble", False)
            if zb and best.tp == 1:
                sched = zb if isinstance(zb, str) else "zbh1"
            else:
                sched = "1f1b"
            self._partition = PipelinePartition(
                self.model, self.loss, blocks, self._mesh, best.pp,
                microbatches=mbs, pp_schedule=sched)

            def train_step(xb, yb):
                loss = self._partition.train_grads(xb, yb)
                self.optimizer.step()
                self.optimizer.clear_grad()
                return loss

            def eval_step(xb, yb):
                out = self.model(xb)
                return self.loss(out, yb)
        else:
            def train_step(xb, yb):
                out = self.model(xb)
                loss = self.loss(out, yb)
                loss.backward()
                self.optimizer.step()
                self.optimizer.clear_grad()
                return loss

            def eval_step(xb, yb):
                out = self.model(xb)
                return self.loss(out, yb)

        self._step = paddle.jit.to_static(
            train_step, objs=[self.model, self.optimizer])
        self._eval_step = paddle.jit.to_static(eval_step,
                                               objs=[self.model])
        return self

    def _shard_batch(self, arr):
        """Place a host batch sharded over the dp axis (GSPMD completes
        the rest of the program's shardings from this seed)."""
        a = arr._data if isinstance(arr, Tensor) else jnp.asarray(arr)
        if self._plan.dp > 1 and a.shape[0] % self._plan.dp == 0:
            spec = P("dp", *([None] * (a.ndim - 1)))
            a = jax.device_put(a, NamedSharding(self._mesh, spec))
        elif len(self._mesh.devices.ravel()) > 1:
            a = jax.device_put(
                a, NamedSharding(self._mesh,
                                 P(*([None] * a.ndim))))
        return Tensor._wrap(a, stop_gradient=True)

    # ------------------------------------------------------------- fit
    def fit(self, train_data, epochs: int = 1, batch_size: int = 32,
            steps_per_epoch: Optional[int] = None, log_freq: int = 0,
            valid_data=None):
        """reference Engine.fit: iterate the data source, one compiled
        step per batch, batch sharded over the planned mesh."""
        if self._step is None:
            self.prepare(global_batch=batch_size)
        loader = self._as_loader(train_data, batch_size)
        for epoch in range(epochs):
            losses = []
            for i, batch in enumerate(loader):
                if steps_per_epoch and i >= steps_per_epoch:
                    break
                xb, yb = batch[0], batch[1]
                with self._mesh:
                    loss = self._step(self._shard_batch(xb),
                                      self._shard_batch(yb))
                losses.append(float(loss._data))
                if log_freq and i % log_freq == 0:
                    print(f"[engine] epoch {epoch} step {i} "
                          f"loss {losses[-1]:.4f}")
            entry = {"epoch": epoch,
                     "loss": float(np.mean(losses)) if losses else None}
            if valid_data is not None:
                entry["eval_loss"] = self.evaluate(valid_data,
                                                   batch_size)
            self.history.append(entry)
        return self.history

    def evaluate(self, eval_data, batch_size: int = 32,
                 steps: Optional[int] = None):
        if self._eval_step is None:
            self.prepare(global_batch=batch_size)
        self.model.eval()
        losses = []
        for i, batch in enumerate(self._as_loader(eval_data, batch_size)):
            if steps and i >= steps:
                break
            with self._mesh:
                loss = self._eval_step(self._shard_batch(batch[0]),
                                       self._shard_batch(batch[1]))
            losses.append(float(loss._data))
        self.model.train()
        return float(np.mean(losses)) if losses else None

    def predict(self, data, batch_size: int = 32):
        import paddle_tpu as paddle
        if self._mesh is None:
            self.prepare(global_batch=batch_size)
        self.model.eval()
        outs = []
        with paddle.no_grad():
            for batch in self._as_loader(data, batch_size):
                xb = batch[0] if isinstance(batch, (list, tuple)) \
                    else batch
                with self._mesh:
                    outs.append(self.model(self._shard_batch(xb)))
        self.model.train()
        return outs

    # ------------------------------------------------------------ misc
    def _as_loader(self, data, batch_size):
        from paddle_tpu.io import DataLoader, Dataset
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=False)
        if hasattr(data, "__next__"):
            # a one-shot iterator/generator would silently train only
            # epoch 0; materialize it so every epoch sees the batches
            return list(data)
        return data                      # any re-iterable of batches

    def save(self, path, training=True):
        import paddle_tpu as paddle
        state = {"model": self.model.state_dict()}
        if training and self.optimizer is not None:
            state["optimizer"] = self.optimizer.state_dict()
        paddle.save(state, path)

    def load(self, path):
        import paddle_tpu as paddle
        state = paddle.load(path)
        self.model.set_state_dict(state["model"])
        if self.optimizer is not None and "optimizer" in state:
            self.optimizer.set_state_dict(state["optimizer"])
        return self
