"""Generic-model partitioner: impose TP/PP on arbitrary Layer models.

Reference being re-designed: the static auto-parallel partitioner +
parallelizer (/root/reference/python/paddle/distributed/auto_parallel/
static/partitioner.py, engine.py:98) — there, a traced program is split
per rank and dist-attrs are completed over it.

TPU-native decomposition:
  * TP ("completion"): parameters of Linear/Embedding layers are
    auto-annotated with mp-axis shardings; the XLA SPMD partitioner
    propagates them through the traced program and inserts the
    collectives (the mp_layers shardings ARE the annotations — this
    generalizes them to layers the user never marked).
  * PP ("partitioner"): the model's dominant homogeneous LayerList is
    located; its blocks' parameters are stacked [L, ...] and the chain
    is compiled onto the 1F1B interleave (parallel/pipeline_1f1b.py).
    The computation BEFORE the blocks (prologue) and AFTER them
    (epilogue + loss) is extracted from the model's own forward by
    shimming the blocks during tracing:
      - prologue: block 0 raises a capture carrying its (traced) input;
      - epilogue: every block becomes identity and the last block
        returns an injected value, so everything downstream computes on
        it (the upstream recompute is dead code XLA eliminates).
    No program-IR surgery — the model's python forward IS the program,
    cut at block boundaries, which is exactly what the reference's
    partitioner does to its static IR.

Contract (same as the reference's PipelineLayer requirement): pp > 1
needs a LayerList/Sequential of structurally identical blocks applied
sequentially; prologue/epilogue may be arbitrary. tp/dp work on ANY
model.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor


# ------------------------------------------------------------------ TP
def annotate_tp(model, mesh: Mesh, axis: str = "mp"):
    """Auto-annotate Linear/Embedding parameters over the mp axis.

    Policy (a generic Megatron-ish completion): Linear weights shard
    their output dim (column) when divisible — falling back to the
    input dim (row) — with column biases sharded to match; Embedding
    weights shard the embedding dim. Everything else stays replicated.
    GSPMD propagates activations/collectives from these seeds, so any
    choice is CORRECT; this one keeps the big GEMM operands sharded.
    Returns the number of annotated parameters.
    """
    from paddle_tpu.nn.layer.common import Linear, Embedding
    tp = mesh.shape[axis]
    if tp <= 1:
        return 0
    n = 0

    def put(t, spec):
        t._assign_array(jax.device_put(
            t._data, NamedSharding(mesh, spec)))
        t._sharding_hint = NamedSharding(mesh, spec)

    for _, sub in model.named_sublayers():
        if isinstance(sub, Linear):
            w = sub.weight
            din, dout = w.shape
            if dout % tp == 0:
                put(w, P(None, axis))
                n += 1
                if sub.bias is not None and sub.bias.shape[0] % tp == 0:
                    put(sub.bias, P(axis))
                    n += 1
            elif din % tp == 0:
                put(w, P(axis, None))
                n += 1
        elif isinstance(sub, Embedding):
            w = sub.weight
            if w.shape[1] % tp == 0:
                put(w, P(None, axis))
                n += 1
    return n


# ------------------------------------------------------------------ PP
def find_pipeline_blocks(model):
    """Locate the dominant homogeneous LayerList: the one with >= 2
    children whose parameter pytrees match in structure AND shapes,
    holding the most parameters. Returns the list of block Layers, or
    None."""
    from paddle_tpu.nn.layer.layers import LayerList, Sequential
    seq_types = (LayerList, Sequential)
    best, best_size = None, 0
    for _, sub in model.named_sublayers():
        if not isinstance(sub, seq_types):
            continue
        children = list(sub)
        if len(children) < 2:
            continue
        sigs = [tuple((name, tuple(p.shape))
                      for name, p in c.named_parameters())
                for c in children]
        if any(s != sigs[0] for s in sigs[1:]):
            continue
        size = sum(int(np.prod(shape)) for _, shape in sigs[0]) \
            * len(children)
        if size > best_size:
            best, best_size = children, size
    return best


class _BlockCapture(Exception):
    def __init__(self, value):
        self.value = value


class PipelinePartition:
    """The pp execution plan for one model: blocks + shim machinery."""

    def __init__(self, model, loss_fn, blocks, mesh: Mesh, pp: int,
                 microbatches: int, pp_schedule: str = "1f1b"):
        if len(blocks) % pp:
            raise ValueError(
                f"{len(blocks)} pipeline blocks not divisible by "
                f"pp={pp}")
        if pp_schedule not in ("1f1b", "zbh1", "zbvpp"):
            raise ValueError(
                f"partitioner pp_schedule must be '1f1b', 'zbh1' or "
                f"'zbvpp', got {pp_schedule!r}")
        if pp_schedule in ("zbh1", "zbvpp") and "mp" in mesh.shape \
                and mesh.shape["mp"] > 1:
            raise ValueError(
                f"pp_schedule={pp_schedule!r} requires a "
                "collective-free stage "
                "body (tp=1): the zero-bubble phases are cond-gated "
                "per stage and GSPMD tp collectives inside a cond "
                "branch deadlock the mesh (gpt_hybrid."
                "_validate_pp_schedule has the full diagnosis)")
        if pp_schedule == "zbvpp" and len(blocks) % (2 * pp):
            raise ValueError(
                f"{len(blocks)} pipeline blocks not divisible by "
                f"2*pp={2 * pp} (pp_schedule='zbvpp' splits the chain "
                "into 2*pp V-placed chunks)")
        self.model = model
        self.loss_fn = loss_fn
        self.blocks = blocks
        self.mesh = mesh
        self.pp = pp
        self.pp_schedule = pp_schedule
        self.microbatches = microbatches
        self.template = blocks[0]
        # param bookkeeping: block params (stacked into the pipeline)
        # vs the rest (prologue+epilogue, differentiated outside)
        self.block_params = []               # [L][(name, Tensor)]
        block_ids = set()
        for b in blocks:
            ps = list(b.named_parameters())
            self.block_params.append(ps)
            block_ids.update(id(p) for _, p in ps)
        self.other_params = [
            (n, p) for n, p in model.named_parameters()
            if id(p) not in block_ids]

    # -- shims ---------------------------------------------------------
    def _run_with_shims(self, shims: dict, x):
        """Run model.forward with selected blocks' forwards replaced."""
        saved = []
        try:
            for b, fn in shims.items():
                saved.append((b, b.__dict__.get("forward")))
                b.__dict__["forward"] = fn
            return self.model(x)
        finally:
            for b, fwd in saved:
                if fwd is None:
                    b.__dict__.pop("forward", None)
                else:
                    b.__dict__["forward"] = fwd

    def prologue(self, x: Tensor):
        """Everything the model computes before block 0, extracted by
        capture-aborting at block 0's entry. Returns (block0_input,
        extra_args, extra_kwargs) — models whose blocks take extra
        arguments (attention masks, position ids: the reference
        PipelineLayer's tuple-valued stage IO, pp_layers.py:56) have
        those captured too; Tensor extras become per-microbatch
        NON-differentiated side inputs of every stage, non-Tensor
        extras stay static."""
        def capture(inp, *a, **k):
            raise _BlockCapture((inp, a, k))
        try:
            self._run_with_shims({self.blocks[0]: capture}, x)
        except _BlockCapture as c:
            return c.value
        raise RuntimeError(
            "pipeline blocks were not reached by model.forward — the "
            "LayerList is not on the forward path")

    def epilogue_loss(self, y: Tensor, x_probe: Tensor, labels):
        """Everything after the last block + the loss, extracted by
        making blocks identity and injecting y at the last block.

        x_probe is THIS microbatch's raw input, so models whose
        epilogue consumes the input or prologue output directly (skip
        connections, loss masks read from ids) stay CORRECT: the
        recomputed prologue inside this call carries the direct-path
        gradient contribution, while the pipeline's dx0 -> prologue
        vjp carries the block-path one; when no skip exists the
        recompute is dead code XLA eliminates."""
        shims = {b: (lambda inp, *a, **k: inp) for b in self.blocks}
        shims[self.blocks[-1]] = lambda inp, *a, **k: y
        out = self._run_with_shims(shims, x_probe)
        if self.loss_fn is not None:
            return self.loss_fn(out, labels)
        return out

    def run_template(self, x: Tensor, param_arrays: List,
                     extra_args=(), extra_kwargs=None) -> Tensor:
        """One block's forward with its params rebound to given arrays
        (the scanned per-layer slices)."""
        tpl = list(self.template.named_parameters())
        saved = [p._data for _, p in tpl]
        try:
            for (_, p), a in zip(tpl, param_arrays):
                p._data = a
            return self.template(x, *extra_args,
                                 **(extra_kwargs or {}))
        finally:
            for (_, p), s in zip(tpl, saved):
                p._data = s

    # -- the pure compiled step ---------------------------------------
    def stacked_blocks(self):
        """[L, ...] arrays per block-param position, sharded
        [pp-on-leading] when placed under the mesh."""
        names = [n for n, _ in self.block_params[0]]
        out = []
        for i, _ in enumerate(names):
            stacked = jnp.stack(
                [self.block_params[li][i][1]._data
                 for li in range(len(self.blocks))])
            out.append(stacked)
        return out

    def train_grads(self, x: Tensor, labels: Tensor):
        """Forward+backward through prologue -> compiled 1F1B over the
        stacked blocks -> epilogue/loss. Returns (loss_Tensor, and sets
        .grad on every model parameter). Runs traced under
        jit.to_static (the Engine wraps it)."""
        import paddle_tpu as paddle
        pp, m = self.pp, self.microbatches
        L = len(self.blocks)
        mesh = self.mesh

        # --- prologue on the full batch (its vjp gives input-side
        # grads for embedding etc.)
        other = self.other_params

        def prologue_fn(other_arrays, x_arr):
            saved = [p._data for _, p in other]
            try:
                for (_, p), a in zip(other, other_arrays):
                    p._data = a
                with paddle.no_grad():
                    h0, a_, _k = self.prologue(Tensor._wrap(x_arr,
                                                            True))
                sides = tuple(a_[i]._data for i in side_pos)
                return (h0._data,) + sides
            finally:
                for (_, p), s in zip(other, saved):
                    p._data = s

        # probe the block-entry signature: record EVERY block's extra
        # call args in one real forward (pass-through shims), so models
        # whose blocks receive per-block-varying extras are rejected
        # loudly instead of silently replaying block 0's values
        records = []

        def _recorder(b):
            orig = b.forward

            def fn(inp, *a, **k):
                records.append((a, k))
                return orig(inp, *a, **k)
            return fn

        with paddle.no_grad():
            self._run_with_shims(
                {b: _recorder(b) for b in self.blocks}, x)
        if len(records) != len(self.blocks):
            raise RuntimeError(
                f"expected {len(self.blocks)} block calls in "
                f"model.forward, saw {len(records)} — blocks must be "
                "applied exactly once each")
        probe_a, probe_k = records[0]
        for kk, vv in probe_k.items():
            if isinstance(vv, Tensor):
                raise NotImplementedError(
                    f"pipeline blocks taking Tensor KWARGS ({kk!r}) "
                    "are not supported — pass tensor side inputs "
                    "positionally")
        def _same_extra(v0, vi):
            """Per-block equality that never silently passes: same
            traced Tensor object => provably same value; otherwise a
            type-aware comparison (array-likes via np.array_equal —
            a bare != would raise ambiguous-truth on them)."""
            if v0 is vi:
                return True
            if isinstance(v0, Tensor) or isinstance(vi, Tensor):
                return False      # distinct (or mixed) tensor objects
            try:
                return bool(v0 == vi)
            except Exception:
                try:
                    return bool(np.array_equal(v0, vi))
                except Exception:
                    return False

        for bi, (a_, k_) in enumerate(records[1:], 1):
            if len(a_) != len(probe_a) or set(k_) != set(probe_k):
                raise NotImplementedError(
                    "pipeline blocks must share one call signature; "
                    f"block {bi} differs from block 0")
            for i, (v0, vi) in enumerate(zip(probe_a, a_)):
                if not _same_extra(v0, vi):
                    raise NotImplementedError(
                        f"block argument {i} varies per block "
                        f"(block 0 vs block {bi}) — the scanned stage "
                        "replays ONE value for all layers; per-block-"
                        "varying extras are not supported by the "
                        "generic partitioner")
            for kk in probe_k:
                if not _same_extra(probe_k[kk], k_[kk]):
                    raise NotImplementedError(
                        f"block kwarg {kk!r} varies per block "
                        f"(block 0 vs block {bi}) — the scanned stage "
                        "replays ONE value for all layers")
        side_pos = [i for i, v in enumerate(probe_a)
                    if isinstance(v, Tensor)]
        if side_pos:
            import warnings
            warnings.warn(
                "pipeline blocks receive tensor side inputs (args "
                f"{side_pos}); these are treated as NON-differentiated "
                "(mask/position-id semantics) — if a side input "
                "depends on trainable prologue parameters, that "
                "gradient path is dropped", stacklevel=2)
        static_args = {i: v for i, v in enumerate(probe_a)
                       if not isinstance(v, Tensor)}
        static_kwargs = dict(probe_k)

        other_arrays = [p._data for _, p in other]
        (x0, *side_arrays), prologue_vjp = jax.vjp(
            prologue_fn, other_arrays, x._data)

        # --- microbatch + stack blocks
        b = x0.shape[0]
        if b % m:
            raise ValueError(f"batch {b} not divisible by "
                             f"microbatches={m}")
        x0 = lax.with_sharding_constraint(
            x0, NamedSharding(mesh, P("dp", *[None] * (x0.ndim - 1)))) \
            if "dp" in mesh.shape and mesh.shape["dp"] > 1 else x0
        mb = x0.reshape((m, b // m) + x0.shape[1:])
        lbl = labels._data
        lbl_mb = lbl.reshape((m, b // m) + lbl.shape[1:])
        # tensor extras become [M, ...] side inputs. Batch-carrying vs
        # batch-free is decided STRUCTURALLY (an eval_shape of the
        # prologue at a different batch size — no compute), not by the
        # leading-dim==batch heuristic, which misfires when a shared
        # [seq, seq] mask happens to have seq == batch
        if side_arrays:
            probe_b = max(1, b // m)
            if probe_b == b:
                probe_b = max(1, b // 2)
            shapes_small = jax.eval_shape(
                prologue_fn,
                [jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in other_arrays],
                jax.ShapeDtypeStruct((probe_b,) + x._data.shape[1:],
                                     x._data.dtype))[1:]
            batchful = [
                sa.ndim >= 1 and sa.shape[0] == b
                and len(ss.shape) >= 1 and ss.shape[0] == probe_b
                and probe_b != b
                for sa, ss in zip(side_arrays, shapes_small)]
        else:
            batchful = []
        side_mb = tuple(
            sa.reshape((m, b // m) + sa.shape[1:]) if bf
            else jnp.broadcast_to(sa[None], (m,) + sa.shape)
            for sa, bf in zip(side_arrays, batchful))

        stacked = self.stacked_blocks()
        if self.pp_schedule == "zbvpp":
            # ZB-V placement: virtual stage sigma owns block chunk
            # sigma; device s holds chunks s (lane 0) and 2pp-1-s
            # (lane 1) -> leaves [pp, 2, Lc, ...]
            Lc = L // (2 * pp)
            vidx = np.stack([np.arange(pp),
                             2 * pp - 1 - np.arange(pp)], axis=1)
            stacked = [
                lax.with_sharding_constraint(
                    s.reshape((2 * pp, Lc) + s.shape[1:])[vidx],
                    NamedSharding(mesh, P("pp", *[None] * (s.ndim + 1))))
                for s in stacked]
        else:
            stacked = [
                lax.with_sharding_constraint(
                    s.reshape((pp, L // pp) + s.shape[1:]),
                    NamedSharding(mesh, P("pp", *[None] * s.ndim)))
                for s in stacked]

        def stage_fn(stage_params, xm, side=()):
            extra = []
            si = iter(side)
            for i in range(len(probe_a)):
                if i in static_args:
                    extra.append(static_args[i])
                else:
                    extra.append(Tensor._wrap(next(si), True))

            def body(h, lp):
                with paddle.no_grad():
                    out = self.run_template(Tensor._wrap(h, True),
                                            list(lp), tuple(extra),
                                            static_kwargs)
                return out._data, None
            h, _ = lax.scan(body, xm, tuple(stage_params))
            return h

        x_mb = x._data.reshape((m, b // m) + x._data.shape[1:])

        def last_grad(y, hp, mb_idx):
            t = lbl_mb[mb_idx]
            x_probe = x_mb[mb_idx]

            def head_loss(hp_, y_):
                saved = [p._data for _, p in other]
                try:
                    for (_, p), a in zip(other, hp_):
                        p._data = a
                    with paddle.no_grad():
                        loss = self.epilogue_loss(
                            Tensor._wrap(y_, True),
                            Tensor._wrap(x_probe, True),
                            Tensor._wrap(t, True))
                    return loss._data / m
                finally:
                    for (_, p), s in zip(other, saved):
                        p._data = s
            (l, (ghp, gy)) = jax.value_and_grad(
                head_loss, argnums=(0, 1))(hp, y)
            return l, gy, ghp

        from paddle_tpu.parallel.pipeline_1f1b import (
            pipeline_train_1f1b, pipeline_train_zbh1,
            pipeline_train_zbvpp)
        from paddle_tpu.core.compat import shard_map
        blk_specs = tuple(P("pp") for _ in stacked)
        pipe_fn = {"zbh1": pipeline_train_zbh1,
                   "zbvpp": pipeline_train_zbvpp,
                   "1f1b": pipeline_train_1f1b}[self.pp_schedule]

        def body(stacked, mb, lbl_mb_, head_arrays, side_mb_):
            return pipe_fn(
                stage_fn, tuple(stacked), mb,
                last_grad, head_params=list(head_arrays),
                side_inputs=side_mb_ if side_mb_ else None)

        loss, sgrads, hgrads, dx0 = shard_map(
            body, mesh=mesh, axis_names={"pp"},
            in_specs=(blk_specs, P(None), P(None), P(None), P(None)),
            out_specs=(P(), blk_specs, P(None), P(None)))(
                tuple(stacked), mb, lbl_mb, other_arrays, side_mb)

        # --- prologue backward from the pipeline's input cotangents
        # (side inputs are non-differentiated: zero cotangents)
        dx0_full = dx0.reshape((b,) + dx0.shape[2:])
        pgrads, _dx = prologue_vjp(
            (dx0_full,) + tuple(jnp.zeros_like(sa)
                                for sa in side_arrays))

        # --- write grads back onto the model's parameters
        for i, (name, p) in enumerate(other):
            g = pgrads[i] + hgrads[i]
            self._acc_grad(p, g)
        for pos in range(len(stacked)):
            if self.pp_schedule == "zbvpp":
                # invert the V gather: chunk sigma's grads sit at
                # [sigma, 0] (sigma < pp) / [2pp-1-sigma, 1]
                g = sgrads[pos]                    # [pp, 2, Lc, ...]
                Lc = L // (2 * pp)
                ds = np.concatenate([np.arange(pp),
                                     np.arange(pp - 1, -1, -1)])
                ls = np.concatenate([np.zeros(pp, np.int64),
                                     np.ones(pp, np.int64)])
                flat = g[ds, ls].reshape((L,) + g.shape[3:])
            else:
                flat = sgrads[pos].reshape(
                    (L,) + sgrads[pos].shape[2:])
            for li in range(L):
                self._acc_grad(self.block_params[li][pos][1], flat[li])
        return Tensor._wrap(loss, True)

    @staticmethod
    def _acc_grad(p, g):
        g = g.astype(p._data.dtype)
        if p.grad is None:
            p.grad = Tensor._wrap(g, True)
        else:
            p.grad = Tensor._wrap(p.grad._data + g, True)
