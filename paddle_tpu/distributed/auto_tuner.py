"""Parallel-config auto-tuner (reference: distributed/auto_tuner/
{prune,utils}.py — grid search with pruning over dp/mp/pp/micro-batch
configs, paired with the elastic manager that acts on live readings).

TPU-native: candidates are (dp, pp, tp, microbatch) factorizations of
the mesh; pruning uses memory/divisibility constraints AND the
analytic planner (``prune_by_planner`` — configs the planner already
refuses are never measured); measurement runs the candidate and scores
it **from the metrics registry** (ISSUE 13): achieved MFU, registry
tokens-per-step-second, steady-state recompiles, bubble fraction and
fetch-wait are read as a snapshot *delta* around the run — no caller
wall clock. Each measured candidate is appended to a JSONL trial log,
so a re-run (same trials_path) warm-starts: completed trials are
skipped and their recorded scores reused.

Legacy mode kept: a ``run_fn`` that returns seconds-per-step is scored
as 1/time (``source="wallclock"``); ``source="auto"`` (default) picks
per candidate based on what run_fn returns.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class Candidate:
    dp: int
    pp: int
    tp: int
    microbatches: int = 1
    sp: bool = False
    zero: int = 0
    remat: bool = True
    time_s: Optional[float] = None
    error: Optional[str] = None
    plan: Optional[object] = None   # full PlanCandidate when planner-guided
    score: Optional[float] = None   # higher is better (tune() fills)
    measurements: Dict[str, object] = field(default_factory=dict)

    @property
    def key(self) -> str:
        """Stable identity for the trial log / warm-start lookup."""
        return (f"dp{self.dp}_pp{self.pp}_tp{self.tp}"
                f"_mb{self.microbatches}_sp{int(self.sp)}"
                f"_z{self.zero}_r{int(self.remat)}")


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(num_devices: int, num_layers: int,
                        global_batch: int, num_heads: int = 0,
                        max_mp: Optional[int] = None) -> List[Candidate]:
    out = []
    for tp in _divisors(num_devices):
        if max_mp and tp > max_mp:
            continue
        if num_heads and num_heads % tp != 0:
            continue
        rest = num_devices // tp
        for pp in _divisors(rest):
            dp = rest // pp
            if num_layers % pp != 0:
                continue  # prune: uneven stage split
            if global_batch % dp != 0:
                continue  # prune: uneven batch shard
            mbs = [m for m in _divisors(global_batch // dp)
                   if pp == 1 or m >= pp] or [1]
            for m in (mbs if pp > 1 else [1]):
                out.append(Candidate(dp=dp, pp=pp, tp=tp, microbatches=m))
    return out


def prune_by_memory(cands: List[Candidate], param_bytes: int,
                    hbm_bytes: int, optimizer_mult: float = 4.0
                    ) -> List[Candidate]:
    """Drop configs whose per-chip weight+opt state can't fit."""
    out = []
    for c in cands:
        shards = c.tp * c.pp
        per_chip = param_bytes * optimizer_mult / shards
        if per_chip <= hbm_bytes * 0.9:
            out.append(c)
    return out


def prune_by_planner(cands: List[Candidate], model_spec, n_chips: int,
                     global_batch: int, chip: str = "v5e"
                     ) -> List[Candidate]:
    """Drop candidates the analytic planner (distributed/planner.py)
    already REFUSES — structurally illegal for the model (heads/hidden
    not divisible by tp, layers by pp, batch by dp) or
    memory-infeasible under the planner's estimate — so tune() never
    spends a measurement on them. Refused candidates get
    ``error="planner_refused: <reason>"`` and a ``autotuner.pruned``
    counter tick per reason; survivors carry their PlanCandidate in
    ``.plan`` (estimate attached) for downstream inspection."""
    from paddle_tpu.distributed.planner import Planner, PlanCandidate

    pl = Planner(chip)
    kept = []
    for c in cands:
        # structural legality answered by the planner itself — one
        # rule set, no drift (Planner.refusal_reason)
        reason = pl.refusal_reason(
            model_spec, n_chips, global_batch, dp=c.dp, tp=c.tp,
            pp=c.pp, microbatches=c.microbatches, zero=c.zero)
        if reason is None:
            p = PlanCandidate(dp=c.dp, tp=c.tp, pp=c.pp, sp=c.sp,
                              zero=c.zero, remat=c.remat,
                              microbatches=c.microbatches)
            pl.estimate(p, model_spec, global_batch)
            if p.est_mem_bytes > pl.hbm_feasible_frac * pl.hbm:
                reason = "planner_mem"
            else:
                c.plan = p
        if reason is None:
            kept.append(c)
        else:
            c.error = f"planner_refused: {reason}"
            _count("autotuner.pruned", reason=reason)
    return kept


class _ModeMixError(RuntimeError):
    """run_fn switched scoring modes mid-sweep — aborts tune()."""


# ------------------------------------------------------------- scoring
def default_score(meas: Dict[str, object]) -> float:
    """Registry-derived candidate score, higher is better.

    Primary signal ladder (first available wins): achieved MFU (the
    ``train.mfu`` gauge — normalized, comparable across configs) ->
    registry tokens-per-step-second (counter delta over step-time
    histogram delta; involves no wall clock) -> 1/mean-step-time.
    Steady-state recompiles beyond a 2-executable allowance divide the
    score — a config that recompiles every step is worthless at any
    throughput."""
    base = meas.get("mfu") or meas.get("tokens_per_s") or 0.0
    if not base:
        mean = meas.get("mean_step_s")
        base = (1.0 / mean) if mean else 0.0
    excess = max(0.0, float(meas.get("compiles") or 0) - 2.0)
    return base / (1.0 + excess)


def _measure_window(delta) -> Dict[str, object]:
    """Distill a snapshot delta (observability.snapshots) into the
    flat measurement dict default_score consumes."""
    step = delta.hist("train.step_time_s")
    # the mfu GAUGE holds whatever the last step wrote — only trust it
    # when this candidate's window recorded steps AND the gauge moved
    # (a run without training.configure() never touches it; a stale
    # reading from the previous candidate must not leak into the
    # score). Identical-MFU candidates fall to the tokens/s signal —
    # a consistent ranking either way.
    mfu = None
    if step["count"]:
        a = delta.after.get("train.mfu")
        b = delta.before.get("train.mfu")
        if a is not None and (b is None
                              or b.get("value") != a.get("value")):
            mfu = a.get("value")
    meas: Dict[str, object] = {
        "steps": step["count"],
        "mean_step_s": step["mean"],
        "tokens": delta.value("train.tokens", default=0.0),
        # tokens per summed step-second — pure registry math
        "tokens_per_s": delta.per("train.tokens", "train.step_time_s"),
        "mfu": mfu,
        "compiles": delta.value("jit.xla_compiles", default=0.0),
        "fetch_wait_s": delta.hist("dataloader.fetch_wait_s")["sum"],
    }
    # bubble fraction: only meaningful when a schedule traced inside
    # the window; report the worst schedule that did
    bubbles = []
    for d in delta.after.series("pipeline.bubble_fraction"):
        lab = d.get("labels") or {}
        if delta.value("pipeline.traces", default=0.0, **lab):
            bubbles.append(d.get("value", 0.0))
    meas["bubble_fraction"] = max(bubbles) if bubbles else None
    return meas


def _count(name, **labels):
    try:
        from paddle_tpu import observability as obs
        if obs.enabled():
            obs.counter(name, **labels).inc()
    except Exception:
        pass


# ----------------------------------------------------------- trial log
def default_trials_path() -> str:
    """Conventional warm-start trial log location, same cache root as
    the attention autotuner's winner table:
    ``$PADDLE_TPU_CACHE_DIR/auto_tuner_trials.jsonl`` (default cache
    root: ``paddle_tpu/.cache/``)."""
    base = os.environ.get("PADDLE_TPU_CACHE_DIR")
    if not base:
        import paddle_tpu
        base = os.path.join(
            os.path.dirname(os.path.abspath(paddle_tpu.__file__)),
            ".cache")
    return os.path.join(base, "auto_tuner_trials.jsonl")


def _load_trials(path: Optional[str]) -> Dict[str, dict]:
    """{candidate key: trial record} from a JSONL trial log; missing
    file -> empty, corrupt lines skipped (a half-written tail from a
    killed run must not poison the warm start)."""
    if not path or not os.path.exists(path):
        return {}
    out: Dict[str, dict] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                out[rec["key"]] = rec
            except (ValueError, KeyError, TypeError):
                continue
    return out


def _append_trial(path: Optional[str], rec: dict) -> None:
    if not path:
        return
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    except OSError as e:
        # losing the warm-start log must not abort a sweep that just
        # spent real measurement time — same never-break-the-job
        # stance as _count
        import sys
        print(f"[auto_tuner] trial log write failed ({e}); "
              "continuing without persistence", file=sys.stderr)


# ---------------------------------------------------------------- tune
def tune(run_fn: Callable[[Candidate], Optional[float]],
         candidates: List[Candidate], warmup: int = 1, iters: int = 3,
         verbose: bool = True, source: str = "auto",
         trials_path: Optional[str] = None,
         score_fn: Callable[[Dict[str, object]], float] = default_score,
         planner_spec: Optional[tuple] = None,
         workload: Optional[str] = None) -> Candidate:
    """Run each candidate and return the best by score.

    run_fn(candidate) executes the candidate's training/serving slice
    (raises on OOM/compile failure). Scoring:

      * run_fn returns seconds-per-step -> legacy WALLCLOCK scoring
        (score = 1/seconds), unchanged contract;
      * run_fn returns None -> TELEMETRY scoring: the run is bracketed
        with registry snapshots and scored by ``score_fn`` over the
        delta (achieved MFU / tokens-per-step-second / recompile
        penalty — see default_score). No wall clock is consulted.
        After the sweep, telemetry candidates are RESCORED on a
        uniform signal — any signal missing for one of them is
        dropped for all, so no candidate is ranked on a different
        scale than its competitors.

    ``source`` pins the mode ("wallclock" | "telemetry"); the default
    "auto" decides per candidate from run_fn's return value (a run_fn
    should be consistent — mixing modes in one sweep makes the scores
    incomparable).

    ``trials_path`` names a JSONL trial log: every finished candidate
    (including failures) is appended, and a warm-started re-run skips
    any candidate whose key is already logged — telemetry trials
    re-enter the uniform rescoring from their logged measurements,
    wallclock/score-only trials keep their recorded score. Pass
    ``workload`` (any stable string naming the model/batch/workload)
    when one trial file serves more than one tuning target: it is
    folded into the lookup key, so trials from a different workload
    are never reused.

    ``planner_spec=(model_spec, n_chips, global_batch[, chip])``
    applies :func:`prune_by_planner` before measuring anything.
    """
    from paddle_tpu.observability import snapshots as _snap

    if planner_spec is not None:
        candidates = prune_by_planner(candidates, *planner_spec)
    prior = _load_trials(trials_path)

    def _k(c: Candidate) -> str:
        return f"{workload}::{c.key}" if workload else c.key

    #: telemetry-measured candidates, rescored uniformly after the loop
    tele: List[Candidate] = []
    #: what "auto" resolved to on the first measured candidate — lets
    #: the rest of a wallclock sweep skip the snapshot bracketing
    resolved: Optional[str] = None

    if source != "auto":
        resolved = source

    for c in candidates:
        rec = prior.get(_k(c))
        # one sweep = ONE scoring mode: wallclock scores (1/s) and
        # telemetry scores (mfu 0..1 / tokens/s) are incomparable
        # scales. The first reused trial or measured candidate pins
        # the sweep's mode; trials recorded under the other mode are
        # never reused (legacy source-less records pass through).
        if (rec is not None and resolved is not None
                and rec.get("source") not in (resolved, None)):
            rec = None
        if rec is not None:
            # warm start: trust the log, skip the measurement
            c.score = rec.get("score")
            c.time_s = rec.get("time_s")
            c.error = rec.get("error")
            c.measurements = rec.get("measurements") or {}
            if c.error is None:
                resolved = resolved or rec.get("source")
            _count("autotuner.trials_skipped")
            if (c.error is None and c.measurements
                    and "time_s" not in c.measurements):
                tele.append(c)
            if verbose:
                print(f"[auto_tuner] {c.key}: warm-start "
                      f"(score={c.score})")
            continue
        mode = source
        try:
            # wallclock sweeps skip the snapshot bracketing — the
            # delta would be computed only to be discarded
            before = (None if resolved == "wallclock"
                      else _snap.Snapshot.take())
            ret = run_fn(c)
            if mode == "auto":
                mode = ("wallclock" if isinstance(ret, (int, float))
                        and not isinstance(ret, bool) else "telemetry")
            if resolved is not None and mode != resolved:
                # run_fn switched modes mid-sweep (either direction):
                # the scores would not be comparable. This is a caller
                # bug, not an infeasible candidate — ABORT the sweep
                # (no trial is logged for it; see the re-raise below)
                raise _ModeMixError(
                    f"run_fn produced a {mode!r}-mode result in a "
                    f"sweep already resolved to {resolved!r} — a "
                    "sweep must not mix scoring modes (did you "
                    "warm-start from a trial log recorded under the "
                    "other mode? pin `source=` or change "
                    "`workload`/`trials_path`)")
            resolved = mode
            if mode == "wallclock":
                c.time_s = float(ret)
                c.score = 1.0 / c.time_s if c.time_s > 0 else 0.0
                c.measurements = {"time_s": c.time_s}
                if verbose:
                    print(f"[auto_tuner] dp={c.dp} pp={c.pp} tp={c.tp} "
                          f"mb={c.microbatches}: "
                          f"{c.time_s * 1e3:.1f} ms/step")
            else:
                c.measurements = _measure_window(
                    _snap.SnapshotDelta(before, _snap.Snapshot.take()))
                # provisional (log/verbose); final ranking rescored
                # uniformly below
                c.score = float(score_fn(c.measurements))
                tele.append(c)
                if c.measurements.get("mean_step_s"):
                    c.time_s = c.measurements["mean_step_s"]
                if verbose:
                    m = c.measurements
                    print(f"[auto_tuner] dp={c.dp} pp={c.pp} tp={c.tp} "
                          f"mb={c.microbatches}: score={c.score:.4g} "
                          f"(mfu={m.get('mfu')}, "
                          f"tok/s={m.get('tokens_per_s')}, "
                          f"compiles={m.get('compiles')})")
            _count("autotuner.trials", source=mode)
        except _ModeMixError:
            raise        # caller bug — never downgraded to a trial
        except Exception as e:  # infeasible candidate
            c.error = f"{type(e).__name__}: {e}"
            if verbose:
                print(f"[auto_tuner] dp={c.dp} pp={c.pp} tp={c.tp} "
                      f"pruned: {c.error[:80]}")
        rec = {
            "key": _k(c), "dp": c.dp, "pp": c.pp, "tp": c.tp,
            "microbatches": c.microbatches, "sp": c.sp,
            "zero": c.zero, "remat": c.remat, "score": c.score,
            "time_s": c.time_s, "error": c.error,
            "measurements": c.measurements,
            # an exception before the mode resolved leaves "auto" —
            # record None so the reuse filter treats it as wildcard
            "source": mode if mode != "auto" else None,
            "workload": workload, "ts": time.time()}
        # a duplicate candidate later in THIS run warm-starts too
        prior[_k(c)] = rec
        _append_trial(trials_path, rec)

    # ---- uniform-signal rescoring: default_score's ladder (mfu ->
    # tokens/s -> 1/step) must pick the SAME rung for every telemetry
    # candidate, or a candidate falling back to tokens/s (thousands)
    # would always beat one scored on mfu (0..1)
    if tele:
        drop_mfu = not all(c.measurements.get("mfu") for c in tele)
        drop_tps = not all(c.measurements.get("tokens_per_s")
                           for c in tele)
        for c in tele:
            meas = dict(c.measurements)
            if drop_mfu:
                meas["mfu"] = None
            if drop_tps:
                meas["tokens_per_s"] = None
            c.score = float(score_fn(meas))

    best: Optional[Candidate] = None
    for c in candidates:
        if c.score is not None and (best is None or c.score > best.score):
            best = c
    if best is None:
        raise RuntimeError("auto_tuner: no feasible candidate")
    try:
        from paddle_tpu import observability as obs
        obs.gauge("autotuner.best_score").set(best.score)
    except Exception:
        pass
    return best


def planner_guided_candidates(model_spec, n_chips: int,
                              global_batch: int, chip: str = "v5e",
                              top_k: int = 8) -> List[Candidate]:
    """Analytic-first search (the reference planner_v2 -> auto-tuner
    handoff): rank the full (dp, tp, pp, sp, zero, remat, microbatch)
    space with the calibrated cost model (distributed/planner.py), then
    hand only the top_k to `tune` for real measurement — replacing the
    blind grid with a model-pruned shortlist."""
    from paddle_tpu.distributed.planner import Planner

    plans = Planner(chip).plan(model_spec, n_chips, global_batch,
                               top_k=top_k)
    return [Candidate(dp=p.dp, pp=p.pp, tp=p.tp,
                      microbatches=p.microbatches, sp=p.sp,
                      zero=p.zero, remat=p.remat, plan=p)
            for p in plans]
