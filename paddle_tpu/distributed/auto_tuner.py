"""Parallel-config auto-tuner (reference: distributed/auto_tuner/
{prune,utils}.py — grid search with pruning over dp/mp/pp/micro-batch
configs).

TPU-native: candidates are (dp, pp, tp, microbatch) factorizations of the
mesh; pruning uses memory/divisibility constraints; measurement jit-runs
the actual train step a few times per candidate.
"""
from __future__ import annotations

import itertools
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class Candidate:
    dp: int
    pp: int
    tp: int
    microbatches: int = 1
    sp: bool = False
    zero: int = 0
    remat: bool = True
    time_s: Optional[float] = None
    error: Optional[str] = None
    plan: Optional[object] = None   # full PlanCandidate when planner-guided


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(num_devices: int, num_layers: int,
                        global_batch: int, num_heads: int = 0,
                        max_mp: Optional[int] = None) -> List[Candidate]:
    out = []
    for tp in _divisors(num_devices):
        if max_mp and tp > max_mp:
            continue
        if num_heads and num_heads % tp != 0:
            continue
        rest = num_devices // tp
        for pp in _divisors(rest):
            dp = rest // pp
            if num_layers % pp != 0:
                continue  # prune: uneven stage split
            if global_batch % dp != 0:
                continue  # prune: uneven batch shard
            mbs = [m for m in _divisors(global_batch // dp)
                   if pp == 1 or m >= pp] or [1]
            for m in (mbs if pp > 1 else [1]):
                out.append(Candidate(dp=dp, pp=pp, tp=tp, microbatches=m))
    return out


def prune_by_memory(cands: List[Candidate], param_bytes: int,
                    hbm_bytes: int, optimizer_mult: float = 4.0
                    ) -> List[Candidate]:
    """Drop configs whose per-chip weight+opt state can't fit."""
    out = []
    for c in cands:
        shards = c.tp * c.pp
        per_chip = param_bytes * optimizer_mult / shards
        if per_chip <= hbm_bytes * 0.9:
            out.append(c)
    return out


def tune(run_fn: Callable[[Candidate], float],
         candidates: List[Candidate], warmup: int = 1, iters: int = 3,
         verbose: bool = True) -> Candidate:
    """run_fn(candidate) -> seconds per step (raises on OOM/compile
    failure). Returns the fastest feasible candidate."""
    best = None
    for c in candidates:
        try:
            t = run_fn(c)
            c.time_s = t
            if verbose:
                print(f"[auto_tuner] dp={c.dp} pp={c.pp} tp={c.tp} "
                      f"mb={c.microbatches}: {t * 1e3:.1f} ms/step")
            if best is None or t < best.time_s:
                best = c
        except Exception as e:  # infeasible candidate
            c.error = f"{type(e).__name__}: {e}"
            if verbose:
                print(f"[auto_tuner] dp={c.dp} pp={c.pp} tp={c.tp} "
                      f"pruned: {c.error[:80]}")
    if best is None:
        raise RuntimeError("auto_tuner: no feasible candidate")
    return best


def planner_guided_candidates(model_spec, n_chips: int,
                              global_batch: int, chip: str = "v5e",
                              top_k: int = 8) -> List[Candidate]:
    """Analytic-first search (the reference planner_v2 -> auto-tuner
    handoff): rank the full (dp, tp, pp, sp, zero, remat, microbatch)
    space with the calibrated cost model (distributed/planner.py), then
    hand only the top_k to `tune` for real measurement — replacing the
    blind grid with a model-pruned shortlist."""
    from paddle_tpu.distributed.planner import Planner

    plans = Planner(chip).plan(model_spec, n_chips, global_batch,
                               top_k=top_k)
    return [Candidate(dp=p.dp, pp=p.pp, tp=p.tp,
                      microbatches=p.microbatches, sp=p.sp,
                      zero=p.zero, remat=p.remat, plan=p)
            for p in plans]
