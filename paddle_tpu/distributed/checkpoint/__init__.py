"""Distributed checkpoint (reference: paddle.distributed.checkpoint —
save_state_dict (save_state_dict.py:145) writes per-rank shard files + a
metadata file with dedup of replicated tensors; load_state_dict re-shards
across changed meshes).

TPU-native: each host writes only its addressable shards (npz) plus a JSON
metadata mapping flat key → shard index-slices → file; load assembles the
global value then device_puts to the *target* sharding, so resharding
across different meshes falls out of placement (the reference needs an
explicit re-shard pass). Async save offloads the host copy to a thread
(orbax-style)."""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

import numpy as np
import jax

from paddle_tpu.core.tensor import Tensor


def _process_index():
    try:
        return jax.process_index()
    except Exception:
        return 0


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False):
    os.makedirs(path, exist_ok=True)
    pid = _process_index()
    flat = _flat(state_dict)
    meta = {"version": 1, "tensors": {}}
    arrays = {}
    for key, t in flat.items():
        if not isinstance(t, Tensor):
            meta["tensors"][key] = {"kind": "python", "value": t}
            continue
        arr = t._data
        sharding = getattr(arr, "sharding", None)
        entries = []
        if sharding is None or sharding.is_fully_replicated:
            # dedup: only the coordinator writes replicated tensors
            if pid == coordinator_rank:
                name = f"{key}.full"
                arrays[name] = np.asarray(arr)
                entries.append({"file": f"shard_{pid}.npz", "name": name,
                                "index": None})
        else:
            seen = set()
            for shard in arr.addressable_shards:
                idx = tuple(
                    (s.start or 0,
                     s.stop if s.stop is not None else dim)
                    for s, dim in zip(shard.index, arr.shape))
                if idx in seen:
                    continue  # dedup replicated copies of the same slice
                seen.add(idx)
                name = f"{key}.{shard.replica_id}.{len(entries)}"
                arrays[name] = np.asarray(shard.data)
                entries.append({"file": f"shard_{pid}.npz", "name": name,
                                "index": [list(i) for i in idx]})
        meta["tensors"][key] = {
            "kind": "tensor",
            "shape": list(arr.shape),
            "dtype": str(np.dtype(arr.dtype)),
            "entries": entries,
        }

    def _write():
        np.savez(os.path.join(path, f"shard_{pid}.npz"), **arrays)
        if pid == coordinator_rank:
            with open(os.path.join(path, "metadata.json"), "w") as f:
                json.dump(meta, f)

    if async_save:
        th = threading.Thread(target=_write, daemon=True)
        th.start()
        return th
    _write()


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, offload: bool = False):
    """Fill `state_dict`'s tensors in place, re-sharding to each target
    tensor's current placement."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    # lazy-load shard files
    files: Dict[str, "np.lib.npyio.NpzFile"] = {}

    def get_arr(file, name):
        if file not in files:
            files[file] = np.load(os.path.join(path, file))
        return files[file][name]

    flat = _flat(state_dict)
    restored_py = {}
    for key, t in flat.items():
        info = meta["tensors"].get(key)
        if info is None:
            continue
        if info["kind"] == "python":
            restored_py[key] = info["value"]
            continue
        full = np.zeros(tuple(info["shape"]),
                        np.dtype(info["dtype"]))
        for e in info["entries"]:
            arr = get_arr(e["file"], e["name"])
            if e["index"] is None:
                full = arr
            else:
                sl = tuple(slice(a, b) for a, b in e["index"])
                full[sl] = arr
        if isinstance(t, Tensor):
            sharding = getattr(t._data, "sharding", None)
            new = jax.device_put(full.astype(t._data.dtype), sharding) \
                if sharding is not None else \
                jax.numpy.asarray(full.astype(t._data.dtype))
            t._assign_array(new)
    for f in files.values():
        f.close()
    if restored_py:
        _write_back_python(state_dict, restored_py)
    return state_dict


def _write_back_python(tree, restored, prefix=""):
    """Restore saved python (non-tensor) values into the nested
    state_dict in place (the reference restores step counters / LR
    schedule scalars on resume). Key layout matches _flat()."""
    for k in list(tree):
        key = f"{prefix}.{k}" if prefix else str(k)
        v = tree[k]
        if isinstance(v, dict):
            _write_back_python(v, restored, key)
        elif not isinstance(v, Tensor) and key in restored:
            tree[k] = restored[key]
