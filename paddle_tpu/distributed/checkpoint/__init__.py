"""Distributed checkpoint (reference: paddle.distributed.checkpoint —
save_state_dict (save_state_dict.py:145) writes per-rank shard files + a
metadata file with dedup of replicated tensors; load_state_dict re-shards
across changed meshes).

TPU-native: each host writes only its addressable shards (npz) plus a JSON
metadata mapping flat key → shard index-slices → file; load assembles the
global value then device_puts to the *target* sharding, so resharding
across different meshes falls out of placement (the reference needs an
explicit re-shard pass). Async save offloads the host copy to a thread
(orbax-style).

Commit protocol (ISSUE 14 satellite): per-file atomicity alone cannot
order the metadata publish against the shard writes — a writer killed
mid-save could leave a READABLE but torn checkpoint (new metadata over
old shards or vice versa). The coordinator therefore removes any stale
``_COMMITTED.json`` FIRST, writes its shard + metadata, and publishes
the commit manifest LAST; ``load_state_dict`` refuses a directory
without a valid manifest or whose manifest references files that do
not exist. Into a FRESH directory (the per-step layout elastic resume
uses via :func:`latest_committed`) this is a complete ordering
guarantee: an interrupted save is simply never committed. NOTE
re-saving into an EXISTING checkpoint dir reuses shard filenames, so
the missing-file check cannot detect a half-overwritten save, and
multi-rank callers must still barrier around save — prefer per-save
directories."""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

import numpy as np
import jax

from paddle_tpu.core.tensor import Tensor

#: commit manifest written LAST by the coordinator; its presence (and
#: the existence of every file it references) defines "committed"
COMMIT_MARKER = "_COMMITTED.json"


def _process_index():
    try:
        return jax.process_index()
    except Exception:
        return 0


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False):
    os.makedirs(path, exist_ok=True)
    pid = _process_index()
    flat = _flat(state_dict)
    meta = {"version": 1, "tensors": {}}
    arrays = {}
    for key, t in flat.items():
        if not isinstance(t, Tensor):
            meta["tensors"][key] = {"kind": "python", "value": t}
            continue
        arr = t._data
        sharding = getattr(arr, "sharding", None)
        entries = []
        if sharding is None or sharding.is_fully_replicated:
            # dedup: only the coordinator writes replicated tensors
            if pid == coordinator_rank:
                name = f"{key}.full"
                arrays[name] = np.asarray(arr)
                entries.append({"file": f"shard_{pid}.npz", "name": name,
                                "index": None})
        else:
            seen = set()
            for shard in arr.addressable_shards:
                idx = tuple(
                    (s.start or 0,
                     s.stop if s.stop is not None else dim)
                    for s, dim in zip(shard.index, arr.shape))
                if idx in seen:
                    continue  # dedup replicated copies of the same slice
                seen.add(idx)
                name = f"{key}.{shard.replica_id}.{len(entries)}"
                arrays[name] = np.asarray(shard.data)
                entries.append({"file": f"shard_{pid}.npz", "name": name,
                                "index": [list(i) for i in idx]})
        meta["tensors"][key] = {
            "kind": "tensor",
            "shape": list(arr.shape),
            "dtype": str(np.dtype(arr.dtype)),
            "entries": entries,
        }

    def _write():
        # atomic PER FILE (tmp + os.replace): a writer killed mid-save
        # never leaves a truncated npz/metadata. ORDERING is the commit
        # manifest's job: drop any stale marker first (the directory is
        # "in progress" from here), publish the marker LAST, and let
        # load verify every referenced file exists. Complete for a
        # fresh directory; re-saves into an existing dir reuse shard
        # names (see module docstring) — prefer per-save dirs.
        if pid == coordinator_rank:
            try:
                os.remove(os.path.join(path, COMMIT_MARKER))
            except FileNotFoundError:
                pass
        # chaos site (ISSUE 15): a fault here models a writer killed
        # mid-save — the stale marker is gone, nothing is committed,
        # and resume must skip this directory
        from paddle_tpu import _chaos
        _chaos.hit("train.checkpoint_save", path=path)
        shard = os.path.join(path, f"shard_{pid}.npz")
        np.savez(shard + ".tmp.npz", **arrays)
        os.replace(shard + ".tmp.npz", shard)
        if pid == coordinator_rank:
            mpath = os.path.join(path, "metadata.json")
            with open(mpath + ".tmp", "w") as f:
                json.dump(meta, f)
            os.replace(mpath + ".tmp", mpath)
            files = sorted({e["file"]
                            for info in meta["tensors"].values()
                            if info.get("kind") == "tensor"
                            for e in info["entries"]})
            marker = {"version": 1, "ts": time.time(),
                      "files": files + ["metadata.json"]}
            cpath = os.path.join(path, COMMIT_MARKER)
            with open(cpath + ".tmp", "w") as f:
                json.dump(marker, f)
            os.replace(cpath + ".tmp", cpath)

    if async_save:
        th = threading.Thread(target=_write, daemon=True)
        th.start()
        return th
    _write()


def _read_marker(path: str) -> Optional[dict]:
    try:
        with open(os.path.join(path, COMMIT_MARKER)) as f:
            marker = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return marker if isinstance(marker, dict) else None


def is_committed(path: str) -> bool:
    """True when ``path`` holds a fully-committed checkpoint: the
    commit manifest exists AND every file it references does too."""
    marker = _read_marker(path)
    if marker is None:
        return False
    return all(os.path.exists(os.path.join(path, f))
               for f in marker.get("files", ()))


def latest_committed(root: str) -> Optional[str]:
    """The newest COMMITTED checkpoint at ``root``: the root itself if
    it is committed, else the newest committed immediate subdirectory
    (by the manifest's commit timestamp, then name). Uncommitted /
    torn / in-progress saves are skipped — this is what elastic resume
    calls so a worker relaunched mid-save never loads a partial
    checkpoint. None when nothing committed exists."""
    if is_committed(root):
        return root
    best = None
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return None
    for name in names:
        sub = os.path.join(root, name)
        if not os.path.isdir(sub) or not is_committed(sub):
            continue
        ts = _read_marker(sub).get("ts", 0)
        if best is None or (ts, name) > best[:2]:
            best = (ts, name, sub)
    return best[2] if best else None


def _assemble_block(info, get_arr, lo, hi, dtype):
    """Assemble the [lo:hi) block of a saved tensor from the entries
    that intersect it. Peak host allocation is O(block) plus O(one
    source entry) — the global array is never materialized."""
    block = np.zeros(tuple(h - l for l, h in zip(lo, hi)), dtype)
    shape = tuple(info["shape"])
    for e in info["entries"]:
        if e["index"] is None:
            src = get_arr(e["file"], e["name"])
            block[...] = src[tuple(slice(l, h)
                                   for l, h in zip(lo, hi))]
            continue
        elo = [a for a, _ in e["index"]]
        ehi = [b for _, b in e["index"]]
        ilo = [max(a, l) for a, l in zip(elo, lo)]
        ihi = [min(b, h) for b, h in zip(ehi, hi)]
        if any(a >= b for a, b in zip(ilo, ihi)):
            continue                      # no overlap with this block
        src_sl = tuple(slice(a - e0, b - e0)
                       for a, b, e0 in zip(ilo, ihi, elo))
        dst_sl = tuple(slice(a - l, b - l)
                       for a, b, l in zip(ilo, ihi, lo))
        block[dst_sl] = get_arr(e["file"], e["name"])[src_sl]
    del shape
    return block


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, offload: bool = False,
                    require_committed: bool = True):
    """Fill `state_dict`'s tensors in place, re-sharding to each target
    tensor's current placement.

    Refuses an UNCOMMITTED checkpoint (no ``_COMMITTED.json``, or a
    manifest referencing missing shard files): a save interrupted
    mid-write is indistinguishable from a valid one by per-file
    inspection alone, and loading it silently corrupts the resume.
    ``require_committed=False`` skips the check for legacy
    checkpoints written before the commit protocol existed.

    SHARD-WISE (VERDICT r2 item 6 / reference load_state_dict.py's
    per-rank read resolution): for a sharded target, only the saved
    entries intersecting each addressable target shard are read and
    assembled per-shard; the device array is built with
    jax.make_array_from_single_device_arrays. Peak host memory is
    O(target shard + one source entry), not O(global tensor) — a
    sharded 7B load no longer needs ~28 GB of host RAM per process.
    Replicated targets still materialize the full value (every device
    holds it by definition)."""
    if require_committed:
        marker = _read_marker(path)
        if marker is None:
            raise ValueError(
                f"checkpoint at {path!r} is not committed (missing or "
                f"unreadable {COMMIT_MARKER}) — the save was "
                "interrupted or is still in progress; pick a committed "
                "checkpoint (latest_committed()) or pass "
                "require_committed=False for pre-protocol checkpoints")
        missing = [f for f in marker.get("files", ())
                   if not os.path.exists(os.path.join(path, f))]
        if missing:
            raise ValueError(
                f"checkpoint at {path!r} is partial: committed "
                f"manifest references missing file(s) {missing} — "
                "refusing to load a torn save")
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    files: Dict[str, "np.lib.npyio.NpzFile"] = {}
    last_entry = {}                       # 1-deep cache: (file,name)->arr

    def get_arr(file, name):
        if last_entry.get("key") == (file, name):
            return last_entry["arr"]
        if file not in files:
            files[file] = np.load(os.path.join(path, file))
        arr = files[file][name]
        last_entry["key"] = (file, name)
        last_entry["arr"] = arr
        return arr

    flat = _flat(state_dict)
    restored_py = {}
    for key, t in flat.items():
        info = meta["tensors"].get(key)
        if info is None:
            continue
        if info["kind"] == "python":
            restored_py[key] = info["value"]
            continue
        if not isinstance(t, Tensor):
            continue
        shape = tuple(info["shape"])
        tgt_dtype = t._data.dtype
        sharding = getattr(t._data, "sharding", None)
        if sharding is not None and not sharding.is_fully_replicated \
                and len(shape):
            dev_map = sharding.addressable_devices_indices_map(shape)
            # one host block alive at a time: each is device_put
            # immediately and only the DEVICE buffer is kept (repeat
            # blocks for replicated dims copy device-to-device)
            dev_blocks = {}
            bufs = []
            for dev, idx in dev_map.items():
                lo = tuple(s.start or 0 for s in idx)
                hi = tuple(s.stop if s.stop is not None else dim
                           for s, dim in zip(idx, shape))
                bkey = (lo, hi)
                if bkey in dev_blocks:
                    bufs.append(jax.device_put(dev_blocks[bkey], dev))
                    continue
                host_block = _assemble_block(info, get_arr, lo, hi,
                                             tgt_dtype)
                buf = jax.device_put(host_block, dev)
                del host_block
                dev_blocks[bkey] = buf
                bufs.append(buf)
            new = jax.make_array_from_single_device_arrays(
                shape, sharding, bufs)
        else:
            full = _assemble_block(info, get_arr, (0,) * len(shape),
                                   shape, tgt_dtype)
            new = jax.device_put(full, sharding) \
                if sharding is not None else jax.numpy.asarray(full)
        t._assign_array(new)
        last_entry.clear()
    for f in files.values():
        f.close()
    if restored_py:
        _write_back_python(state_dict, restored_py)
    return state_dict


def _write_back_python(tree, restored, prefix=""):
    """Restore saved python (non-tensor) values into the nested
    state_dict in place (the reference restores step counters / LR
    schedule scalars on resume). Key layout matches _flat()."""
    for k in list(tree):
        key = f"{prefix}.{k}" if prefix else str(k)
        v = tree[k]
        if isinstance(v, dict):
            _write_back_python(v, restored, key)
        elif not isinstance(v, Tensor) and key in restored:
            tree[k] = restored[key]
