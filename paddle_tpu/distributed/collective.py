"""Collective ops.

Reference: the ProcessGroup collective surface (phi/core/distributed/
collective/process_group.h:48) + python communication ops
(python/paddle/distributed/communication/*).

TPU-native split:
- **Inside parallel regions** (shard_map/jit): the `ops.*` functions below
  are thin wrappers over lax collectives (psum/all_gather/ppermute/
  all_to_all) keyed by mesh axis name — these compile onto ICI. This is
  the path all performance-relevant code uses.
- **Eager single-controller**: collectives across the "group of devices"
  are expressed on *sharded arrays*: all_reduce = reshard partial→replicate
  (XLA inserts the psum), all_gather = reshard shard→replicate, etc. Each
  eager call returns a completed _Task for reference API parity (the
  NCCL-stream async Task semantics collapse — XLA async dispatch already
  overlaps).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.observability import metrics as _met
from .env import Group, get_world_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class _Task:
    """Completed-collective handle (ProcessGroup::Task parity)."""

    def __init__(self, out=None):
        self._out = out

    def wait(self):
        if self._out is not None:
            self._out._data.block_until_ready()
        return True

    def is_completed(self):
        return True


# ---------------------------------------------------------------------------
# in-jit collectives over a named mesh axis (the perf path)
# ---------------------------------------------------------------------------
class ops:
    """lax collectives keyed by mesh axis — use inside shard_map."""

    @staticmethod
    def psum(x, axis_name):
        return jax.lax.psum(x, axis_name)

    @staticmethod
    def pmean(x, axis_name):
        return jax.lax.pmean(x, axis_name)

    @staticmethod
    def pmax(x, axis_name):
        return jax.lax.pmax(x, axis_name)

    @staticmethod
    def all_gather(x, axis_name, axis=0, tiled=True):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)

    @staticmethod
    def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
        return jax.lax.psum_scatter(x, axis_name,
                                    scatter_dimension=scatter_dimension,
                                    tiled=tiled)

    @staticmethod
    def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
        return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis,
                                  tiled=tiled)

    @staticmethod
    def ppermute(x, axis_name, perm):
        return jax.lax.ppermute(x, axis_name, perm)

    @staticmethod
    def axis_index(axis_name):
        return jax.lax.axis_index(axis_name)


# ---------------------------------------------------------------------------
# eager API-parity collectives on (possibly sharded) tensors
# ---------------------------------------------------------------------------
# ---------------------------------------------------------------------------
# collective deferral (DataParallel.no_sync / gradient accumulation)
# ---------------------------------------------------------------------------
# While a deferral context is open, framework-fired gradient-sync
# collectives (all_reduce/reduce/reduce_scatter and hook-fired grad
# re-lays) are RECORDED instead of executed, deduped by key, and replayed
# once on context exit against the then-current (accumulated) tensors —
# the reference no_sync contract (parallel.py DataParallel.no_sync):
# skip grad comm until the last microbatch.
_defer_stack: list = []


class _DeferredCalls:
    def __init__(self):
        self.calls = {}            # key -> fn (last registration wins)
        self.skipped = 0

    def add(self, key, fn):
        if key in self.calls:
            self.skipped += 1
        self.calls[key] = fn

    def flush(self):
        for fn in self.calls.values():
            fn()
        self.calls.clear()


def deferral_active():
    return bool(_defer_stack)


def defer_or_run(key, fn):
    """Run fn now, unless a deferral context is open — then record it
    (deduped by key; replayed once at context exit)."""
    if _defer_stack:
        _defer_stack[-1].add(key, fn)
        return None
    return fn()


class defer_collectives:
    """Context manager deferring grad-sync collectives until exit."""

    def __enter__(self):
        _defer_stack.append(_DeferredCalls())
        return self

    def __exit__(self, exc_type, exc, tb):
        rec = _defer_stack.pop()
        if exc_type is None:
            rec.flush()
        return False


def _collective_log(op, tensor, group, n_tensors=1):
    if _met._ENABLED:
        _met.REGISTRY.counter("collective.calls", op=op).inc()
        try:
            a = tensor._data if isinstance(tensor, Tensor) else tensor
            nbytes = (int(np.prod(a.shape))
                      * np.dtype(a.dtype).itemsize * n_tensors)
            _met.REGISTRY.counter("collective.bytes", op=op).inc(nbytes)
        except Exception:
            pass           # object collectives / None payloads
    from paddle_tpu.core.flags import get_flag
    if get_flag("FLAGS_collective_debug"):
        import sys
        shape = list(tensor.shape) if hasattr(tensor, "shape") else "?"
        gid = getattr(group, "id", "world") if group is not None \
            else "world"
        print(f"[collective] {op} group={gid} shape={shape}",
              file=sys.stderr)


def _world(group):
    return group.nranks if group is not None else get_world_size()


def _dev_count():
    return len(jax.devices())


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op=True):
    """Eager single-controller semantics: a replicated array is already
    globally consistent → identity. A device-sharded array holds different
    data only along ARRAY dims (there is no per-rank hidden copy to
    reduce), so the per-rank allreduce of the reference maps to
    collective.ops.psum/pmax/... inside shard_map — use that in parallel
    regions. A sharded eager input is gathered to replicated (its global
    value is unchanged; no reduction is performed)."""
    _collective_log("all_reduce", tensor, group)
    if deferral_active():
        # NOTE: deduped by tensor identity — callers syncing a tensor
        # that is REPLACED each microbatch (param grads) must defer at
        # their own level keyed by the stable owner instead
        # (fused_allreduce_gradients does; stage-2 hooks do)
        _defer_stack[-1].add(("all_reduce", id(tensor), id(group)),
                             lambda: all_reduce(tensor, op, group,
                                                sync_op))
        return _Task(tensor)
    sharding = getattr(tensor._data, "sharding", None)
    if sharding is not None and not sharding.is_fully_replicated:
        tensor._data = jax.device_put(
            tensor._data,
            NamedSharding(sharding.mesh, P(*([None] * tensor.ndim))))
    return _Task(tensor)


def all_gather(tensor_list: List[Tensor], tensor: Tensor,
               group: Optional[Group] = None, sync_op=True):
    _collective_log("all_gather", tensor, group)
    n = _world(group)
    for _ in range(n - len(tensor_list)):
        tensor_list.append(None)
    for i in range(n):
        tensor_list[i] = Tensor._wrap(tensor._data)
    return _Task(tensor)


def all_gather_object(object_list, obj, group=None):
    n = _world(group)
    object_list.clear()
    object_list.extend([obj] * n)


def broadcast(tensor: Tensor, src=0, group=None, sync_op=True):
    _collective_log("broadcast", tensor, group)
    return _Task(tensor)


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def reduce(tensor: Tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    _collective_log("reduce", tensor, group)
    return _Task(tensor)


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None,
            sync_op=True):
    _collective_log("scatter", tensor, group)
    if tensor_list:
        tensor._assign_array(tensor_list[0]._data)
    return _Task(tensor)


def reduce_scatter(tensor: Tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    _collective_log("reduce_scatter", tensor, group)
    if deferral_active():
        _defer_stack[-1].add(("reduce_scatter", id(tensor), id(group)),
                             lambda: reduce_scatter(tensor, tensor_list,
                                                    op, group, sync_op))
        return _Task(tensor)
    if tensor_list:
        acc = tensor_list[0]._data
        tensor._assign_array(acc)
    return _Task(tensor)


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    _collective_log("alltoall", in_tensor_list[0] if in_tensor_list
                    else None, group, n_tensors=len(in_tensor_list))
    out_tensor_list.clear()
    out_tensor_list.extend([Tensor._wrap(t._data) for t in in_tensor_list])
    return _Task(None)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    _collective_log("alltoall_single", in_tensor, group)
    if out_tensor is not None:
        out_tensor._assign_array(in_tensor._data)
        return _Task(out_tensor)
    return _Task(in_tensor)


def send(tensor: Tensor, dst=0, group=None, sync_op=True):
    _collective_log("send", tensor, group)
    return _Task(tensor)


def recv(tensor: Tensor, src=0, group=None, sync_op=True):
    _collective_log("recv", tensor, group)
    return _Task(tensor)


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks


def wait(tensor, group=None, use_calc_stream=True):
    tensor._data.block_until_ready()
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Single-controller semantics like scatter/all_gather above: every
    rank's shard is this process's tensor (reference
    communication/gather.py)."""
    _collective_log("gather", tensor, group)
    n = _world(group)
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend([Tensor._wrap(tensor._data) for _ in range(n)])
    return _Task(tensor)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    if in_object_list:
        out_object_list.clear()
        out_object_list.append(in_object_list[0])
    return None
