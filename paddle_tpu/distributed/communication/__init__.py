"""paddle.distributed.communication namespace (reference:
python/paddle/distributed/communication/ — the group ops live in
..collective here; this package adds the stream.* variants)."""
from ..collective import (  # noqa: F401
    all_gather, all_gather_object, all_reduce, alltoall, alltoall_single,
    batch_isend_irecv, broadcast, broadcast_object_list, gather, irecv,
    isend, P2POp, recv, reduce, reduce_scatter, ReduceOp, scatter,
    scatter_object_list, send, wait,
)
from . import stream  # noqa: F401
