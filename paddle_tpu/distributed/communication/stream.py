"""paddle.distributed.communication.stream equivalent (reference:
communication/stream/*.py — collectives issued on an explicit comm
stream, returning async Tasks).

TPU framing: XLA owns stream ordering; `use_calc_stream` has no
hardware meaning, so every stream.* op is the plain collective with an
async-looking Task handle (SURVEY §2.6 — the async Task/stream
semantics collapse into XLA's async collectives)."""
from __future__ import annotations

from .. import collective as _c

__all__ = ["all_reduce", "all_gather", "alltoall", "alltoall_single",
           "broadcast", "reduce", "reduce_scatter", "scatter", "send",
           "recv"]


def _task(result=None):
    return _c._Task(result)


def all_reduce(tensor, op=_c.ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    return _c.all_reduce(tensor, op, group, sync_op=True)


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    return _c.all_gather(tensor_or_tensor_list, tensor, group,
                         sync_op=True)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True,
             use_calc_stream=False):
    # NOTE reference stream.alltoall takes (out, in)
    return _c.alltoall(in_tensor_list, out_tensor_list, group,
                       sync_op=True)


def alltoall_single(out_tensor, in_tensor, out_split_sizes=None,
                    in_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    return _c.alltoall_single(in_tensor, out_tensor, in_split_sizes,
                              out_split_sizes, group, sync_op=True)


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    return _c.broadcast(tensor, src, group, sync_op=True)


def reduce(tensor, dst=0, op=_c.ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    return _c.reduce(tensor, dst, op, group, sync_op=True)


def reduce_scatter(tensor, tensor_or_tensor_list, op=_c.ReduceOp.SUM,
                   group=None, sync_op=True, use_calc_stream=False):
    return _c.reduce_scatter(tensor, tensor_or_tensor_list, op, group,
                             sync_op=True)


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    return _c.scatter(tensor, tensor_or_tensor_list, src, group,
                      sync_op=True)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    return _c.send(tensor, dst, group, sync_op=True)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _c.recv(tensor, src, group, sync_op=True)
