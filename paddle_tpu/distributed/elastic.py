"""Elastic training (reference: ElasticManager,
fleet/elastic/manager.py:125 — etcd heartbeat membership, scale in/out,
trainer relaunch; distributed/elastic.py:21).

TPU-native: membership rides the JAX coordination service when available;
this module provides the heartbeat/membership state machine against a
pluggable KV store (file-based store for single-host + tests, etcd-style
interface for clusters) and the relaunch decision logic.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional


def _wrap_ttl(value: str, ttl_s: Optional[float]) -> str:
    return json.dumps({"value": value,
                       "expires": time.time() + ttl_s if ttl_s else None})


def _unwrap_ttl(raw) -> Optional[str]:
    """Decoded value, or None if malformed/expired."""
    value, _expired = _decode_ttl(raw)
    return value


def _decode_ttl(raw):
    """(value, expired): value is None when malformed OR expired;
    expired is True only for a well-formed entry past its TTL — the
    distinction lets FileKVStore physically purge expired files while
    leaving foreign/malformed files alone."""
    try:
        payload = json.loads(raw)
    except (json.JSONDecodeError, UnicodeDecodeError, TypeError):
        return None, False
    if not isinstance(payload, dict) or "value" not in payload:
        return None, False  # e.g. raw counters mirrored into kv space
    if payload.get("expires") and payload["expires"] < time.time():
        return None, True
    return payload["value"], False


class KVStore:
    """Pluggable store interface (etcd analog)."""

    def put(self, key: str, value: str, ttl_s: Optional[float] = None):
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        raise NotImplementedError

    def delete(self, key: str):
        raise NotImplementedError


class FileKVStore(KVStore):
    """Shared-filesystem store (works across hosts on NFS/GCS-fuse)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key):
        return os.path.join(self.root, key.replace("/", "__"))

    def put(self, key, value, ttl_s=None):
        # unique tmp per writer: concurrent puts of the SAME key (a
        # watchdog's arm-time publish racing its monitor thread's
        # startup publish, or two hosts heartbeating one shared key)
        # must not steal each other's tmp file — os.replace stays the
        # single atomic point and last-writer-wins
        tmp = self._path(key) + \
            f".{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "w") as f:
            f.write(_wrap_ttl(value, ttl_s))
        os.replace(tmp, self._path(key))

    def get_prefix(self, prefix):
        out = {}
        p = prefix.replace("/", "__")
        for fn in os.listdir(self.root):
            if not fn.startswith(p) or fn.endswith(".tmp"):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    raw = f.read()
            except OSError:
                continue
            value, expired = _decode_ttl(raw)
            if value is not None:
                out[fn.replace("__", "/")] = value
            elif expired:
                # lazy GC: a long-running job heartbeats forever and
                # would otherwise grow the store unboundedly with dead
                # nodes' files. Racing a concurrent re-put is benign:
                # worst case one fresh heartbeat file is dropped and
                # the next heartbeat (heartbeat_s later) restores it.
                try:
                    os.remove(os.path.join(self.root, fn))
                except OSError:
                    pass
        return out

    def delete(self, key):
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class TCPKVStore(KVStore):
    """KVStore over the native C++ TCPStore (native/src/store.cc) — the
    in-cluster etcd stand-in when no shared filesystem exists. TTLs are
    enforced read-side from an expiry stamp in the payload, matching
    FileKVStore semantics."""

    def __init__(self, host: str, port: int, is_master: bool = False):
        from paddle_tpu.native import TCPStore
        self._store = TCPStore(host, port, is_master=is_master)

    def put(self, key, value, ttl_s=None):
        self._store.set(key, _wrap_ttl(value, ttl_s))

    def get_prefix(self, prefix):
        out = {}
        for key, raw in self._store.list(prefix).items():
            value, expired = _decode_ttl(raw)
            if value is not None:
                out[key] = value
            elif expired:
                # lazy GC, matching FileKVStore: a long-running job's
                # store must not grow unboundedly with dead nodes'
                # keys. Only well-formed expired entries are removed;
                # foreign/malformed values are left alone. Racing a
                # concurrent re-put is benign (the next heartbeat
                # restores the key).
                try:
                    self._store.delete_key(key)
                except Exception:
                    pass
        return out

    def delete(self, key):
        self._store.delete_key(key)


def run_resilient(fn: Callable[[int], object], *, max_restarts: int = 3,
                  backoff_s: float = 0.5, backoff_factor: float = 2.0,
                  max_backoff_s: float = 30.0,
                  restartable=(Exception,), on_restart=None):
    """Single-host supervised restart (ISSUE 15): the in-process
    analog of the launcher's ``--max_restarts`` relaunch loop, for
    loops that recover from *catchable* crashes — an injected chaos
    fault, a poisoned step, a transient runtime error — without
    paying process teardown.

    Calls ``fn(attempt)`` (attempt 0 first). When fn raises a
    ``restartable`` exception, waits ``backoff_s * backoff_factor **
    (attempt-1)`` (capped at ``max_backoff_s``), ticks the
    ``train.restarts`` counter, calls ``on_restart(attempt, exc)`` if
    given, and calls fn again — at most ``max_restarts`` restarts,
    then the last exception propagates. ``KeyboardInterrupt`` /
    ``SystemExit`` always propagate (the operator's ctrl-C must win).

    Recovery of *state* is fn's job: build the loop with a
    ``hapi.FaultTolerantCheckpoint`` (or call
    ``training.load_train_checkpoint``) so every attempt resumes from
    ``checkpoint.latest_committed()`` — the resume-equivalence test
    proves crash+resume reproduces the uninterrupted run bitwise.
    NOTE a ``training.NonFiniteStepError`` abort is deterministic for
    a given data shard; restarting replays the same garbage, so the
    breaker fires again and the supervisor gives up after the bounded
    retries — by design it never converts a diagnostic abort into an
    infinite crash loop."""
    attempt = 0
    while True:
        try:
            return fn(attempt)
        except (KeyboardInterrupt, SystemExit):
            raise
        except restartable as e:
            attempt += 1
            if attempt > max_restarts:
                raise
            from paddle_tpu.observability import metrics as _met
            if _met._ENABLED:
                _met.REGISTRY.counter("train.restarts").inc()
            if on_restart is not None:
                on_restart(attempt, e)
            delay = min(backoff_s * (backoff_factor ** (attempt - 1)),
                        max_backoff_s)
            if delay > 0:
                time.sleep(delay)


class ElasticManager:
    """Heartbeat + membership watcher (manager.py:125 semantics):
    each node heartbeats `{prefix}/nodes/{rank}` with a TTL; the watcher
    detects join/leave and calls on_change(world) so the trainer can
    checkpoint + relaunch with new endpoints."""

    def __init__(self, store: KVStore, job_id: str, rank: int,
                 np_range: Optional[tuple] = None, heartbeat_s: float = 2.0,
                 ttl_s: float = 6.0,
                 on_change: Optional[Callable[[List[int]], None]] = None):
        self.store = store
        self.prefix = f"elastic/{job_id}"
        self.rank = rank
        self.heartbeat_s = heartbeat_s
        self.ttl_s = ttl_s
        self.on_change = on_change
        self.np_min, self.np_max = np_range or (1, 1 << 30)
        self._stop = threading.Event()
        self._threads = []
        self._last_world: List[int] = []

    def world(self) -> List[int]:
        nodes = self.store.get_prefix(f"{self.prefix}/nodes/")
        return sorted(int(k.rsplit("/", 1)[-1]) for k in nodes)

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            self.store.put(f"{self.prefix}/nodes/{self.rank}",
                           json.dumps({"ts": time.time()}),
                           ttl_s=self.ttl_s)
            self._stop.wait(self.heartbeat_s)

    def _watch_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            w = self.world()
            if w != self._last_world:
                prev = self._last_world
                self._last_world = w
                if prev and self.on_change is not None:
                    self.on_change(w)

    def start(self):
        self.store.put(f"{self.prefix}/nodes/{self.rank}",
                       json.dumps({"ts": time.time()}), ttl_s=self.ttl_s)
        self._last_world = self.world()
        for target in (self._heartbeat_loop, self._watch_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        self.store.delete(f"{self.prefix}/nodes/{self.rank}")

    def healthy(self) -> bool:
        n = len(self.world())
        return self.np_min <= n <= self.np_max
