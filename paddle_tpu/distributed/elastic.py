"""Elastic training (reference: ElasticManager,
fleet/elastic/manager.py:125 — etcd heartbeat membership, scale in/out,
trainer relaunch; distributed/elastic.py:21).

TPU-native: membership rides the JAX coordination service when available;
this module provides the heartbeat/membership state machine against a
pluggable KV store (file-based store for single-host + tests, etcd-style
interface for clusters) and the relaunch decision logic.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional


class KVStore:
    """Pluggable store interface (etcd analog)."""

    def put(self, key: str, value: str, ttl_s: Optional[float] = None):
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        raise NotImplementedError

    def delete(self, key: str):
        raise NotImplementedError


class FileKVStore(KVStore):
    """Shared-filesystem store (works across hosts on NFS/GCS-fuse)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key):
        return os.path.join(self.root, key.replace("/", "__"))

    def put(self, key, value, ttl_s=None):
        payload = {"value": value,
                   "expires": time.time() + ttl_s if ttl_s else None}
        tmp = self._path(key) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path(key))

    def get_prefix(self, prefix):
        out = {}
        p = prefix.replace("/", "__")
        for fn in os.listdir(self.root):
            if not fn.startswith(p) or fn.endswith(".tmp"):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    payload = json.load(f)
            except (json.JSONDecodeError, OSError):
                continue
            if payload.get("expires") and payload["expires"] < time.time():
                continue
            out[fn.replace("__", "/")] = payload["value"]
        return out

    def delete(self, key):
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class ElasticManager:
    """Heartbeat + membership watcher (manager.py:125 semantics):
    each node heartbeats `{prefix}/nodes/{rank}` with a TTL; the watcher
    detects join/leave and calls on_change(world) so the trainer can
    checkpoint + relaunch with new endpoints."""

    def __init__(self, store: KVStore, job_id: str, rank: int,
                 np_range: Optional[tuple] = None, heartbeat_s: float = 2.0,
                 ttl_s: float = 6.0,
                 on_change: Optional[Callable[[List[int]], None]] = None):
        self.store = store
        self.prefix = f"elastic/{job_id}"
        self.rank = rank
        self.heartbeat_s = heartbeat_s
        self.ttl_s = ttl_s
        self.on_change = on_change
        self.np_min, self.np_max = np_range or (1, 1 << 30)
        self._stop = threading.Event()
        self._threads = []
        self._last_world: List[int] = []

    def world(self) -> List[int]:
        nodes = self.store.get_prefix(f"{self.prefix}/nodes/")
        return sorted(int(k.rsplit("/", 1)[-1]) for k in nodes)

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            self.store.put(f"{self.prefix}/nodes/{self.rank}",
                           json.dumps({"ts": time.time()}),
                           ttl_s=self.ttl_s)
            self._stop.wait(self.heartbeat_s)

    def _watch_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            w = self.world()
            if w != self._last_world:
                prev = self._last_world
                self._last_world = w
                if prev and self.on_change is not None:
                    self.on_change(w)

    def start(self):
        self.store.put(f"{self.prefix}/nodes/{self.rank}",
                       json.dumps({"ts": time.time()}), ttl_s=self.ttl_s)
        self._last_world = self.world()
        for target in (self._heartbeat_loop, self._watch_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        self.store.delete(f"{self.prefix}/nodes/{self.rank}")

    def healthy(self) -> bool:
        n = len(self.world())
        return self.np_min <= n <= self.np_max
