"""Process-level distributed environment.

Reference: init_parallel_env (python/paddle/distributed/parallel.py:978),
TCPStore rendezvous (phi/core/distributed/store/tcp_store.h:121),
ProcessGroup registry (parallel.py:1145).

TPU-native: multi-host bootstrap is jax.distributed.initialize (the TPU
coordination service plays TCPStore's role); within a host, JAX is
single-controller over all local chips, so "rank" maps to
jax.process_index() (one controller per host), not one rank per chip.
Collective *compute* rides XLA ops inside jit/shard_map — the eager Group
API below exists for reference-API parity and for orchestration logic.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax


class Group:
    """Communication group handle (reference Group, parallel.py:219 area)."""

    def __init__(self, rank: int, ranks: List[int], gid: int = 0,
                 name: Optional[str] = None):
        # rank is the GLOBAL rank; store the group-local rank (-1 = not a
        # member), matching the reference Group semantics
        self.ranks = list(ranks)
        self.rank = self.ranks.index(rank) if rank in self.ranks else -1
        self.nranks = len(ranks)
        self.id = gid
        self._name = name or f"group_{gid}"

    @property
    def name(self):
        return self._name

    @property
    def world_size(self):
        return self.nranks

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks}, " \
               f"ranks={self.ranks})"


_GROUPS = {}
_GLOBAL_GROUP: Optional[Group] = None
_INITIALIZED = False
_NEXT_GID = 1


def is_initialized() -> bool:
    return _INITIALIZED


def init_parallel_env() -> Group:
    """Bootstrap. Multi-host (PADDLE_TRAINERS_NUM>1 or JAX coordinator env
    set): jax.distributed.initialize over the coordination service.
    Single-host: trivially initialized."""
    global _INITIALIZED, _GLOBAL_GROUP
    if _INITIALIZED:
        return _GLOBAL_GROUP
    coord = os.environ.get("PADDLE_MASTER") or \
        os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if nprocs > 1 and coord and not jax._src.distributed.global_state.client:
        # PADDLE_MASTER conventionally carries host:port; fall back to
        # MASTER_PORT only when no port is embedded
        host, _, port = coord.partition(":")
        port = port or os.environ.get("MASTER_PORT", "8476")
        jax.distributed.initialize(
            coordinator_address=f"{host}:{port}",
            num_processes=nprocs, process_id=pid)
    _INITIALIZED = True
    world = list(range(get_world_size()))
    _GLOBAL_GROUP = Group(get_rank(), world, gid=0, name="global_group")
    _GROUPS[0] = _GLOBAL_GROUP
    return _GLOBAL_GROUP


def get_rank(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.rank
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    try:
        return jax.process_count()
    except Exception:
        return 1


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    global _NEXT_GID
    if ranks is None:
        ranks = list(range(get_world_size()))
    g = Group(get_rank() if get_rank() in ranks else -1, list(ranks),
              gid=_NEXT_GID)
    _GROUPS[_NEXT_GID] = g
    _NEXT_GID += 1
    return g


def get_group(gid: int = 0) -> Optional[Group]:
    return _GROUPS.get(gid)


def destroy_process_group(group=None):
    global _INITIALIZED, _GLOBAL_GROUP
    if group is None:
        _GROUPS.clear()
        _GLOBAL_GROUP = None
        _INITIALIZED = False
    else:
        _GROUPS.pop(group.id, None)


def barrier(group=None):
    """Single-host: device sync. Multi-host: a coordination-service sync
    (the real cross-process barrier)."""
    import jax.numpy as jnp
    if get_world_size() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    else:
        jnp.zeros(()).block_until_ready()


class ParallelEnv:
    """reference paddle.distributed.ParallelEnv"""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
