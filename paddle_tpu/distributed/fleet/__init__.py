"""Fleet facade (reference: fleet/fleet.py:151 — fleet.init builds the
HybridCommunicateGroup from DistributedStrategy; distributed_model wraps by
strategy (fleet/model.py:32); distributed_optimizer wraps with
HybridParallelOptimizer (hybrid_parallel_optimizer.py:258))."""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax

from paddle_tpu.nn.layer.layers import Layer
from ..env import get_rank, get_world_size, init_parallel_env
from ..mesh import ProcessMesh, set_mesh
from ..parallel import DataParallel
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       get_hybrid_communicate_group,
                       set_hybrid_communicate_group)
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding,
                        get_rng_state_tracker, model_parallel_random_seed)
from .recompute import recompute, recompute_sequential
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc
from .sequence_parallel_utils import (
    ColumnSequenceParallelLinear, RowSequenceParallelLinear,
    mark_as_sequence_parallel_parameter,
    register_sequence_parallel_allreduce_hooks)
from ..ps import PaddleCloudRoleMaker  # noqa: F401


class Role:
    """reference fleet/base/role_maker.py Role constants."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference UserDefinedRoleMaker: explicit role/ranks instead of
    env discovery."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective)
        self._role = {Role.WORKER: "TRAINER",
                      Role.SERVER: "PSERVER"}.get(
            kwargs.get("current_id_role", kwargs.get("role",
                                                     Role.WORKER)),
            "TRAINER")
        if "role" in kwargs:
            self._role = {Role.WORKER: "TRAINER",
                          Role.SERVER: "PSERVER"}[kwargs["role"]]
        self._worker_id = int(kwargs.get("current_id", 0))
        self._num_workers = int(kwargs.get("worker_num", 1))
        self._servers = list(kwargs.get("server_endpoints", []))


class UtilBase:
    """reference fleet/utils/fs UtilBase shell: barrier/all-gather
    helpers for user scripts."""

    def barrier(self, comm_world="worker"):
        from ..env import barrier as _b
        _b()

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np
        return np.asarray(input)

    def get_file_shard(self, files):
        from ..env import get_rank, get_world_size
        return [f for i, f in enumerate(files)
                if i % get_world_size() == get_rank()]


class MultiSlotDataGenerator:
    """reference distributed/fleet/data_generator: user subclasses
    generate() yielding (slot_name, values) pairs; run() streams the
    MultiSlot text format to stdout for the DataFeed."""

    def generate_sample(self, line):
        raise NotImplementedError

    def _format(self, sample):
        out = []
        for _name, values in sample:
            out.append(str(len(values)))
            out += [str(v) for v in values]
        return " ".join(out)

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            gen = self.generate_sample(line)
            for sample in (gen() if callable(gen) else gen):
                sys.stdout.write(self._format(sample) + chr(10))


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    pass

__all__ = [
    "init", "DistributedStrategy", "distributed_model",
    "distributed_optimizer", "get_hybrid_communicate_group",
    "PaddleCloudRoleMaker", "UserDefinedRoleMaker", "Role",
    "UtilBase", "Fleet", "MultiSlotDataGenerator",
    "MultiSlotStringDataGenerator", "is_server", "is_worker", "init_server",
    "run_server", "init_worker", "stop_worker",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "get_rng_state_tracker", "recompute",
    "LayerDesc", "PipelineLayer",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
    "mark_as_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
]


class DistributedStrategy:
    """reference fleet/base/distributed_strategy.py:284 (protobuf-backed);
    here a plain typed config."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.without_graph_optimization = False


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_init = False
        self._role_maker = None
        self._ps_server = None
        self._ps_client = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        if role_maker is not None and not is_collective:
            # parameter-server mode (reference fleet PS flow)
            self._role_maker = role_maker
            self._strategy = strategy or DistributedStrategy()
            self._is_init = True
            return self
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        dims = {
            "pp": hc.get("pp_degree", 1),
            "sep": hc.get("sep_degree", 1),
            "mp": hc.get("mp_degree", 1),
            "sharding": hc.get("sharding_degree", 1),
            "dp": hc.get("dp_degree", 1),
        }
        total = int(np.prod(list(dims.values())))
        ndev = len(jax.devices())
        if total == 1 and ndev > 1:
            dims["dp"] = ndev
            total = ndev
        if total > ndev:
            raise ValueError(
                f"hybrid config needs {total} devices, have {ndev}")
        topo = CommunicateTopology(list(dims), list(dims.values()))
        self._hcg = HybridCommunicateGroup(topo)
        set_hybrid_communicate_group(self._hcg)
        set_mesh(self._hcg.process_mesh)
        self._is_init = True
        return self

    @property
    def worker_num(self):
        return get_world_size()

    def worker_index(self):
        return get_rank()

    def is_first_worker(self):
        return get_rank() == 0

    def barrier_worker(self):
        from ..env import barrier
        barrier()

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model: Layer):
        """Wrap by strategy (reference fleet/model.py:32 wrapping order
        :143-162). On TPU the TP/PP layers already annotated their
        shardings at construction; DP replication is applied here."""
        hcg = self._hcg
        if hcg is None:
            raise RuntimeError("call fleet.init first")
        if hcg.get_pipe_parallel_world_size() > 1 and \
                isinstance(model, PipelineLayer):
            model.build_pipeline(hcg)
            # the reference wraps pipeline models into PipelineParallel
            # (fleet/model.py:143) whose train_batch drives the
            # schedule selected by pipeline_configs["schedule_mode"];
            # the compiled analog shares the auto-parallel
            # partitioner's executor (meta_parallel.py). dp>1 needs NO
            # DataParallel wrapper here: the partitioner shards the
            # batch over the mesh's dp axis inside the compiled step
            # (partitioner.py:367) — eager hook-bucketed DP on top
            # would double the reduction
            from .meta_parallel import (PipelineParallel,
                                        UnpartitionableModel)
            try:
                return PipelineParallel(model, hcg, self._strategy)
            except (UnpartitionableModel, NotImplementedError) as e:
                # heterogeneous chains / sep-sharding hybrids keep the
                # old pass-through behavior (forward works; train_batch
                # needs a partitionable chain) instead of hard-failing
                # at wrap time
                import warnings
                warnings.warn(
                    f"fleet PipelineParallel unavailable for this "
                    f"model ({e}); falling back to the plain wrap path "
                    "(the pipeline layer as-is, DataParallel-wrapped "
                    "when dp_degree > 1 — forward/eval works; use the "
                    "auto-parallel Engine or the hybrid engine for "
                    "pipelined training)", stacklevel=2)
        if hcg.get_data_parallel_world_size() > 1:
            model = DataParallel(model, mesh=hcg.process_mesh)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        strategy = strategy or self._strategy
        opt = HybridParallelOptimizer(optimizer, self._hcg, strategy)
        if strategy is not None and getattr(strategy, "gradient_merge",
                                            False):
            # reference auto_parallel_gradient_merge pass: k-step
            # accumulation OUTSIDE the (possibly sharded) update
            from paddle_tpu.optimizer.gradient_merge import \
                GradientMergeOptimizer
            cfgs = getattr(strategy, "gradient_merge_configs", {}) or {}
            return GradientMergeOptimizer(
                opt, k_steps=int(cfgs.get("k_steps", 1)),
                avg=bool(cfgs.get("avg", True)))
        return opt

    # --------------------------------------------- parameter-server mode
    # (reference fleet.py init_server/run_server/init_worker/stop_worker)
    def is_server(self):
        return self._role_maker is not None and \
            self._role_maker.is_server()

    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def init_server(self, *args, **kwargs):
        from ..ps import PsServer
        rm = self._role_maker
        self._ps_server = PsServer(
            host="0.0.0.0", port=rm.server_port(),
            num_workers=rm.worker_num())

    def run_server(self):
        if self._ps_server is None:
            self.init_server()
        self._ps_server.run()

    def init_worker(self, scopes=None):
        from ..ps import PsClient
        self._ps_client = PsClient(self._role_maker.server_endpoints())

    def stop_worker(self):
        if self._ps_client is not None:
            if self._role_maker.is_first_worker():
                self._ps_client.stop_servers()
            self._ps_client.close()
            self._ps_client = None

    @property
    def ps_client(self):
        return self._ps_client


class HybridParallelOptimizer:
    """reference hybrid_parallel_optimizer.py:258: grad clip across groups
    + sharded update. Cross-shard grad-norm reductions are emitted by XLA
    from shardings, so this reduces to delegation + optional ZeRO
    placement of optimizer states."""

    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if hcg is not None and \
                hcg.get_sharding_parallel_world_size() > 1:
            from ..api import ShardingStage1, shard_optimizer
            self._inner_opt = shard_optimizer(
                optimizer, ShardingStage1("sharding", hcg.process_mesh))

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad


Fleet = _Fleet
_fleet = _Fleet()


def init(role_maker=None, is_collective=True, strategy=None,
         log_level="INFO"):
    return _fleet.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return _fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _fleet.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group_():
    return _fleet.get_hybrid_communicate_group()


def worker_num():
    return _fleet.worker_num


def worker_index():
    return _fleet.worker_index()


def is_server():
    return _fleet.is_server()


def is_worker():
    return _fleet.is_worker()


def init_server(*args, **kwargs):
    return _fleet.init_server(*args, **kwargs)


def run_server():
    return _fleet.run_server()


def init_worker(scopes=None):
    return _fleet.init_worker(scopes)


def stop_worker():
    return _fleet.stop_worker()

# fleet.auto: the auto-parallel namespace (reference fleet's `auto`
# re-export of distributed.auto_parallel — Engine, shard_* API, planner)
from paddle_tpu.distributed import auto_parallel as auto  # noqa: E402,F401
__all__.append("auto")
