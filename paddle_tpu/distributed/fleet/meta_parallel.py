"""Fleet-side pipeline training: PipelineParallel.train_batch.

Reference being re-designed: fleet/meta_parallel/pipeline_parallel.py
(`PipelineParallel.train_batch`, :547) — the host-scheduled 1F1B loop
fleet users drive directly, with the schedule selected by
`DistributedStrategy.pipeline_configs["schedule_mode"]`
(distributed_strategy.py pipeline section; the zero-bubble passes hook
in through the same knob). TPU-native: train_batch compiles the WHOLE
step — prologue -> compiled pipeline over the block chain -> epilogue/
loss -> optimizer update — into one XLA program via the auto-parallel
partitioner (the same machinery Engine.prepare uses), so the fleet
facade and the Engine share one pipeline executor instead of two
schedulers.

schedule_mode mapping (reference names, case-insensitive):
  "1F1B"          -> compiled 1F1B (pipeline_1f1b.pipeline_train_1f1b)
  "ZBH1"          -> compiled zero-bubble ZBH1
  "ZBVPP" / "ZBV" -> compiled zero-bubble ZB-V
  "FThenB"        -> refused with a pointer (the compiled executor's
                     memory bound comes from 1F1B; F-then-B's only
                     role in the reference is simplicity)
"""
from __future__ import annotations

import time

import numpy as np

from paddle_tpu import _chaos
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.observability import metrics as _met
from paddle_tpu.observability import training as _otrain


class UnpartitionableModel(ValueError):
    """The model's structure cannot take the compiled pipeline executor
    (no homogeneous block run / unsupported hybrid axes) — a STRUCTURAL
    limitation distributed_model treats as pass-through, unlike config
    errors (bad schedule_mode), which must surface."""


class PipelineParallel(Layer):
    """Wrap a PipelineLayer for fleet-driven pipeline training."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        from paddle_tpu.distributed.auto_parallel.partitioner import (
            PipelinePartition, find_pipeline_blocks)
        import jax
        from jax.sharding import Mesh

        pp = hcg.get_pipe_parallel_world_size()
        if pp <= 1:
            raise ValueError("PipelineParallel needs pp_degree > 1")
        self._steps_seen = 0
        topo = hcg.topology()
        for ax in ("sep", "sharding"):
            if ax in topo.get_hybrid_group_names() and \
                    topo.get_dim(ax) > 1:
                raise NotImplementedError(
                    f"fleet PipelineParallel with {ax}_degree > 1: use "
                    "the hybrid engine (models/gpt_hybrid.py) or the "
                    "auto-parallel Engine for sep/sharding hybrids")
        cfg = getattr(strategy, "pipeline_configs", None) or {}
        mode = str(cfg.get("schedule_mode", "1F1B")).lower()
        sched = {"1f1b": "1f1b", "zbh1": "zbh1",
                 "zbvpp": "zbvpp", "zbv": "zbvpp"}.get(mode)
        self._sched_error = None
        if sched is None:
            # unsupported schedule_mode is a TRAIN-path config error:
            # raising here would also kill forward/eval-only flows that
            # never call train_batch, so the wrap keeps working as a
            # plain facade and train_batch() raises (reference configs
            # routinely carry FThenB/VPP/Eager1F1B strings that only
            # matter once train_batch runs)
            self._sched_error = (
                f"pipeline_configs schedule_mode {mode!r}: supported "
                "modes are 1F1B, ZBH1, ZBVPP/ZBV (FThenB's compiled "
                "analog is the GPipe rotation — parallel/pipeline.py — "
                "kept off this facade because 1F1B strictly bounds its "
                "memory)")
            self._layers = layers
            self._partition = None
            self._mesh = None
            self._sched = None
            self._step = None
            self._opt = None
            self._micro_bs = cfg.get("micro_batch_size")
            return
        # accumulate_steps maps 1:1 onto pipeline microbatches (the
        # reference feeds accumulate_steps micro-batches per
        # train_batch); the default 1 runs a single microbatch — a deep
        # bubble, but exactly what unset reference configs do. The
        # batch must divide accumulate_steps (the partitioner's
        # microbatching contract).
        micro = max(1, int(cfg.get("accumulate_steps", 1)))
        self._micro_bs = cfg.get("micro_batch_size")

        # the PipelineLayer desc chain mixes prologue/epilogue entries
        # (embedding lambdas, the head) with the homogeneous block run;
        # take the longest contiguous run of structurally identical
        # children — the partitioner shims everything before/after it
        # into the prologue/epilogue
        blocks = self._longest_homogeneous_run(
            list(getattr(layers, "run_function", [])))
        if not blocks:
            blocks = find_pipeline_blocks(layers)
        if not blocks:
            raise UnpartitionableModel(
                "PipelineParallel needs a homogeneous block run in its "
                "layer chain (the reference PipelineLayer contract); "
                "none found on this model")
        dp = hcg.get_data_parallel_world_size()
        mp = hcg.get_model_parallel_world_size()
        n = dp * pp * mp
        # keep the hcg topology's device layout (pp outermost, then
        # mp, then dp — topology._ORDER with the size-1 sep/sharding
        # axes squeezed): stage s of the compiled mesh must be the
        # same devices hcg.get_pipe_parallel_group() reports, or
        # reference-style code keyed on stage identity disagrees with
        # where the program actually placed the stages
        devs = np.asarray(jax.devices()[:n]).reshape(pp, mp, dp)
        mesh = Mesh(devs.transpose(2, 0, 1), ("dp", "pp", "mp"))
        self._layers = layers
        self._partition = PipelinePartition(
            layers, getattr(layers, "_loss_fn", None), blocks, mesh,
            pp, microbatches=micro, pp_schedule=sched)
        self._mesh = mesh
        self._sched = sched
        self._step = None
        self._opt = None

    @staticmethod
    def _longest_homogeneous_run(children):
        sigs = [tuple((n, tuple(p.shape))
                      for n, p in c.named_parameters())
                for c in children]
        best, cur = [], []
        for c, s in zip(children, sigs):
            if cur and s and s == cur[-1][1]:
                cur.append((c, s))
            else:
                cur = [(c, s)]
            if len(cur) > len(best):
                best = list(cur)
        return [c for c, _ in best] if len(best) >= 2 else None

    # transparent layer facade -----------------------------------------
    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @property
    def pp_schedule(self):
        return self._sched

    def train_batch(self, data, optimizer, lr_scheduler=None,
                    scaler=None, step_guard=None, watchdog=None):
        """One pipelined train step (reference train_batch contract):
        data = (inputs, labels); runs forward+backward through the
        compiled pipeline, applies the optimizer, steps the scheduler.
        The whole step is one jitted program (compiled on first call,
        reused after).

        Robustness hooks (ISSUE 15): ``watchdog`` — a
        ``TrainStepWatchdog`` armed around the step; a stall aborts
        with a ``TrainHangError`` straggler report instead of hanging.
        ``step_guard`` — a ``training.StepGuard`` run POST-step
        (``observe_loss``): the fused program already applied the
        update, so the guard detects non-finite losses and
        circuit-breaks, while skip-step semantics belong to the
        eager/hapi path."""
        if self._sched_error is not None:
            raise ValueError(self._sched_error)
        if scaler is not None:
            raise NotImplementedError(
                "train_batch with a GradScaler: use amp.auto_cast "
                "inside the loss or the hybrid engine's AMP path")
        x0 = data[0]
        bs = x0.shape[0]
        if self._micro_bs and \
                bs != self._partition.microbatches * int(self._micro_bs) \
                and not getattr(self, "_mb_warned", False):
            import warnings
            warnings.warn(
                f"pipeline_configs: batch {bs} != accumulate_steps "
                f"({self._partition.microbatches}) * micro_batch_size "
                f"({self._micro_bs}); the batch is split into "
                f"accumulate_steps microbatches of {bs // self._partition.microbatches} "
                "— micro_batch_size is informational here",
                stacklevel=2)
            self._mb_warned = True
        if self._step is None or self._opt is not optimizer:
            import paddle_tpu as paddle

            part = self._partition

            def _step(xb, yb):
                loss = part.train_grads(xb, yb)
                optimizer.step()
                optimizer.clear_grad()
                return loss

            self._step = paddle.jit.to_static(
                _step, objs=[self._layers, optimizer])
            self._opt = optimizer
        x, y = data
        step_idx = self._steps_seen
        if watchdog is not None:
            watchdog.step_begin(step_idx)
        t0 = time.perf_counter()
        try:
            _chaos.hit("train.step", step=step_idx)
            with self._mesh:
                loss = self._step(x, y)
            if step_guard is not None or watchdog is not None:
                # sync inside the armed window: a hung collective
                # must trip the watchdog, not escape as an async value
                loss_val = float(loss)
        except KeyboardInterrupt:
            err = watchdog.consume_abort() if watchdog is not None \
                else None
            if err is not None:
                raise err from None
            raise
        finally:
            if watchdog is not None:
                watchdog.step_end()
        self._steps_seen += 1
        if step_guard is not None:
            step_guard.observe_loss(loss_val, step=step_idx)
        if _met._ENABLED:
            # close the timing window on the step's completion, not its
            # async dispatch (a dispatch-only window reports impossible
            # tokens/s on a real accelerator); metrics-off runs keep
            # full dispatch pipelining
            try:
                import jax
                jax.block_until_ready(loss._data)
            except Exception:
                pass
            tokens = None
            arr = getattr(x0, "_data", None)
            if arr is not None and arr.ndim >= 2 and \
                    np.issubdtype(np.dtype(arr.dtype), np.integer):
                tokens = int(arr.shape[0]) * int(arr.shape[1])
            _otrain.record_step(time.perf_counter() - t0,
                                samples=int(bs), tokens=tokens)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
