"""Tensor-parallel (Megatron-style) layers.

Reference: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding :47,
ColumnParallelLinear :334, RowParallelLinear :541, ParallelCrossEntropy;
comm ops mp_ops.py; TP RNG tracker mpu/random.py:34.

TPU-native: instead of manual identity/allreduce PyLayers around sharded
GEMMs, each layer (a) device_puts its weight with the right NamedSharding
over the 'mp' mesh axis and (b) constrains activations with
with_sharding_constraint — GSPMD then inserts exactly the collectives the
reference codes by hand (allreduce after RowParallel, allgather for
gather_output, etc.), and overlaps them with compute.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer
from ..mesh import ProcessMesh, get_mesh
from .topology import get_hybrid_communicate_group


def _mp_axis():
    hcg = get_hybrid_communicate_group()
    if hcg is not None and hcg.get_model_parallel_world_size() > 1:
        return hcg.process_mesh, "mp"
    mesh = get_mesh()
    if mesh is not None and "mp" in mesh.dim_names:
        return mesh, "mp"
    return None, None


def _put(param, spec):
    mesh, _ = _mp_axis()
    if mesh is None:
        return
    ns = NamedSharding(mesh.jax_mesh, spec)
    param._assign_array(jax.device_put(param._data, ns))
    param._sharding_hint = ns


def _constrain(arr, spec):
    mesh, _ = _mp_axis()
    if mesh is None:
        return arr
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh.jax_mesh, spec))
    except Exception:
        return arr


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp'
    (reference mp_layers.py:47 — the masked-local-lookup + allreduce
    becomes a sharded gather GSPMD partitions)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = True
        _put(self.weight, P("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    """weight [in, out] sharded on out over 'mp'
    (reference mp_layers.py:334)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = True
        self.bias = self.create_parameter(
            (out_features,), None, is_bias=True) if has_bias else None
        _put(self.weight, P(None, "mp"))
        if self.bias is not None:
            self.bias.is_distributed = True
            _put(self.bias, P("mp"))

    def forward(self, x):
        def f(a, w, *b):
            out = jnp.matmul(a, w)
            if b:
                out = out + b[0]
            if self.gather_output:
                out = _constrain(
                    out, P(*([None] * out.ndim)))
            else:
                out = _constrain(
                    out, P(*([None] * (out.ndim - 1) + ["mp"])))
            return out
        args = (x, self.weight) + ((self.bias,) if self.bias is not None
                                   else ())
        return run_op("column_parallel_linear", f, *args)


class RowParallelLinear(Layer):
    """weight [in, out] sharded on in over 'mp'; contraction over the
    sharded dim makes GSPMD emit the allreduce the reference does manually
    (reference mp_layers.py:541)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = True
        self.bias = self.create_parameter(
            (out_features,), None, is_bias=True) if has_bias else None
        _put(self.weight, P("mp", None))

    def forward(self, x):
        def f(a, w, *b):
            if self.input_is_parallel:
                a = _constrain(a, P(*([None] * (a.ndim - 1) + ["mp"])))
            out = jnp.matmul(a, w)
            out = _constrain(out, P(*([None] * out.ndim)))
            if b:
                out = out + b[0]
            return out
        args = (x, self.weight) + ((self.bias,) if self.bias is not None
                                   else ())
        return run_op("row_parallel_linear", f, *args)


class ParallelCrossEntropy(Layer):
    """Cross entropy over 'mp'-sharded logits (reference mp_layers.py
    ParallelCrossEntropy) — softmax over the sharded class dim; GSPMD
    handles the max/sum reductions across shards."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class RNGStatesTracker:
    """TP-aware RNG (reference mpu/random.py:34): named per-region
    generators so dropout inside TP regions differs per shard while
    weights init identically."""

    def __init__(self):
        self._states = {}

    def add(self, name, seed):
        from paddle_tpu.core.generator import Generator
        self._states[name] = Generator(seed)

    def rng_state(self, name="global_seed"):
        import contextlib

        @contextlib.contextmanager
        def guard():
            from paddle_tpu.core import generator as gen_mod
            if name in self._states:
                prev = gen_mod._DEFAULT
                gen_mod._DEFAULT = self._states[name]
                try:
                    yield
                finally:
                    gen_mod._DEFAULT = prev
            else:
                yield
        return guard()


_RNG_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_TRACKER


def model_parallel_random_seed(seed=None):
    import random as _r
    seed = seed if seed is not None else _r.randint(0, 2 ** 31 - 1)
    _RNG_TRACKER.add("global_seed", seed)
    _RNG_TRACKER.add("local_seed", seed + 1024)
