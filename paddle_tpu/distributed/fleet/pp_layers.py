"""Pipeline-parallel layer descriptors.

Reference: PipelineLayer (fleet/meta_parallel/parallel_layers/
pp_layers.py:257), LayerDesc (:56), SharedLayerDesc (:76), and the 1F1B /
interleaved schedules (meta_parallel/pipeline_parallel.py:547,:1143).

TPU-native: a single controller owns every stage, so "which rank holds
which layer" becomes "which pp-mesh coordinate the stage's weights are
sharded onto". For uniform decoder stacks the idiomatic TPU pipeline is
stacked-stage weights + shard_map over the 'pp' axis with ppermute
microbatch rotation — implemented functionally in
paddle_tpu.parallel.pipeline and used by the model zoo. PipelineLayer here
keeps the reference's descriptor/segmentation surface and executes the
full stack (correct on any mesh; the compiled pipeline path is opt-in).
"""
from __future__ import annotations

import math
import re
from typing import Callable, List, Optional, Union

from paddle_tpu.nn.layer.layers import Layer, LayerList


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight shared across stages (e.g. embedding/output head,
    reference pp_layers.py:76)."""

    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._layer_descs = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval
        self._shared = {}
        self.run_function = LayerList()
        self._build_all()
        self._stage_bounds = self._segment(len(self.run_function),
                                           self._num_stages)

    def _build_all(self):
        for i, desc in enumerate(self._layer_descs):
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name in self._shared:
                    layer = self._shared[desc.layer_name]
                else:
                    layer = desc.build_layer()
                    self._shared[desc.layer_name] = layer
                fwd = desc.forward_func
                if fwd is not None:
                    layer = _FnWrap(layer, fwd)
                self.run_function.append(layer)
            elif isinstance(desc, LayerDesc):
                self.run_function.append(desc.build_layer())
            elif isinstance(desc, Layer):
                self.run_function.append(desc)
            elif callable(desc):
                self.run_function.append(_Lambda(desc))
            else:
                raise TypeError(f"bad pipeline entry {desc!r}")

    @staticmethod
    def _segment(n, stages):
        per = [n // stages + (1 if i < n % stages else 0)
               for i in range(stages)]
        bounds = [0]
        for p in per:
            bounds.append(bounds[-1] + p)
        return bounds

    def get_stage_from_index(self, idx):
        for s in range(self._num_stages):
            if self._stage_bounds[s] <= idx < self._stage_bounds[s + 1]:
                return s
        return self._num_stages - 1

    def build_pipeline(self, hcg):
        """Annotate stage activations onto the pp mesh axis."""
        self._hcg = hcg
        return self

    def forward(self, x, **kwargs):
        from .recompute import recompute
        out = x
        for i, layer in enumerate(self.run_function):
            if self._recompute_interval > 0 and \
                    i % self._recompute_interval == 0 and self.training:
                out = recompute(layer, *(out if isinstance(out, tuple)
                                         else (out,)))
            else:
                out = layer(*(out if isinstance(out, tuple) else (out,)))
        return out

    def loss(self, output, label):
        if self._loss_fn is None:
            raise RuntimeError("no loss_fn configured")
        return self._loss_fn(output, label)


class _Lambda(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class _FnWrap(Layer):
    def __init__(self, layer, fn):
        super().__init__()
        self.inner = layer
        self._fn = fn

    def forward(self, *args):
        return self._fn(self.inner, *args)
