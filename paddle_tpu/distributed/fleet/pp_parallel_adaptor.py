"""Pipeline-parallel checkpoint layout converter.

Reference: fleet/utils/pp_parallel_adaptor.py (PipeLineModelAdaptor) —
converts per-stage PipelineLayer checkpoints saved under one pipeline
configuration (pp degree, virtual-pp degree) into another, by renaming
the per-stage-local layer indices through the global layer order and
re-splitting into the destination stages.

TPU design: a checkpoint here is a plain dict per stage mapping
parameter names like "layers.<local_idx>.<param>" (the PipelineLayer
naming), plus shared/non-layer entries replicated to the stages that
reference them. The converter is functional — dicts in, dicts out — so
it composes with paddle_tpu.framework.io.save/load and the sharded
distributed.checkpoint path.
"""
from __future__ import annotations

import re
import warnings
from typing import Dict, List, Sequence

__all__ = ["ParallelConfig", "PipeLineModelAdaptor",
           "convert_pp_state_dicts"]

_LAYER_RE = re.compile(r"^layers\.(\d+)\.(.+)$")


def _values_equal(a, b) -> bool:
    import numpy as np
    try:
        a, b = np.asarray(a), np.asarray(b)
    except Exception:
        return a is b
    try:
        # NaN-containing replicas are still replicas
        return bool(np.array_equal(a, b, equal_nan=True))
    except TypeError:   # equal_nan unsupported for this dtype
        return bool(np.array_equal(a, b))


class ParallelConfig:
    """Pipeline layout description (reference pp_parallel_adaptor.py
    ParallelConfig, reduced to the axes the conversion needs)."""

    def __init__(self, pp: int, vpp: int = 1):
        if pp < 1 or vpp < 1:
            raise ValueError("pp and vpp must be >= 1")
        self.pp = pp
        self.vpp = vpp

    def stage_chunks(self, num_layers: int) -> List[List[int]]:
        """Global layer ids held by each stage, in local order.

        With vpp > 1 a stage holds vpp interleaved chunks (reference
        VPP assignment: chunk c of stage s covers layers
        [(c*pp + s) * L/(pp*vpp), ...))."""
        total_chunks = self.pp * self.vpp
        if num_layers % total_chunks != 0:
            raise ValueError(
                f"{num_layers} layers not divisible by pp*vpp="
                f"{total_chunks}")
        per = num_layers // total_chunks
        out = []
        for s in range(self.pp):
            mine: List[int] = []
            for c in range(self.vpp):
                start = (c * self.pp + s) * per
                mine.extend(range(start, start + per))
            out.append(mine)
        return out


def _split_stage_dict(stage_dict: Dict, layer_ids: Sequence[int]):
    """(per-global-layer params, passthrough non-layer params)."""
    by_layer: Dict[int, Dict[str, object]] = {g: {} for g in layer_ids}
    passthrough: Dict[str, object] = {}
    for name, value in stage_dict.items():
        m = _LAYER_RE.match(name)
        if m is None:
            passthrough[name] = value
            continue
        local = int(m.group(1))
        if local >= len(layer_ids):
            raise KeyError(
                f"param {name}: local layer {local} out of range for a "
                f"stage holding {len(layer_ids)} layers")
        by_layer[layer_ids[local]][m.group(2)] = value
    return by_layer, passthrough


def convert_pp_state_dicts(stage_dicts: Sequence[Dict],
                           src: ParallelConfig,
                           dst: ParallelConfig) -> List[Dict]:
    """Re-partition per-stage state dicts from layout src to dst.

    Layer params are renamed through global layer ids; non-layer
    entries (shared embeddings, final norm, ...) are replicated to
    EVERY destination stage — a stage model that does not reference an
    entry simply ignores it, while tied-embedding stages (first/last)
    always find their copy. Same-named entries held by several source
    stages are treated as replicas (first seen wins); a warning is
    emitted if the replicas are not numerically identical."""
    if len(stage_dicts) != src.pp:
        raise ValueError(f"expected {src.pp} stage dicts, "
                         f"got {len(stage_dicts)}")
    num_layers = sum(
        len({int(m.group(1)) for m in map(_LAYER_RE.match, d)
             if m is not None}) for d in stage_dicts)
    src_chunks = src.stage_chunks(num_layers)
    dst_chunks = dst.stage_chunks(num_layers)

    global_params: Dict[int, Dict[str, object]] = {}
    passthrough: Dict[str, object] = {}
    for stage_dict, layer_ids in zip(stage_dicts, src_chunks):
        by_layer, extra = _split_stage_dict(stage_dict, layer_ids)
        global_params.update(by_layer)
        for k, v in extra.items():
            if k in passthrough:
                if not _values_equal(passthrough[k], v):
                    warnings.warn(
                        f"non-layer checkpoint entry {k!r} appears in "
                        "multiple source stages with different values; "
                        "keeping the first-seen copy")
            else:
                passthrough[k] = v

    out: List[Dict] = []
    for layer_ids in dst_chunks:
        d: Dict[str, object] = {}
        for local, g in enumerate(layer_ids):
            for pname, value in global_params[g].items():
                d[f"layers.{local}.{pname}"] = value
        d.update(passthrough)
        out.append(d)
    return out


class PipeLineModelAdaptor:
    """Reference-shaped driver (fleet/utils/pp_parallel_adaptor.py):
    holds the two layouts and converts checkpoint dicts between them."""

    def __init__(self, src_parallel_config: ParallelConfig,
                 dst_parallel_config: ParallelConfig):
        self._src = src_parallel_config
        self._dst = dst_parallel_config

    def apply(self, stage_dicts: Sequence[Dict]) -> List[Dict]:
        return convert_pp_state_dicts(stage_dicts, self._src, self._dst)

    def peek_model(self, stage_dicts: Sequence[Dict]) -> List[str]:
        """List the converted parameter names per stage (reference
        peek utility for checkpoint inspection)."""
        return ["; ".join(sorted(d)) for d in self.apply(stage_dicts)]
