"""Activation recomputation (reference: fleet/recompute/recompute.py —
RecomputeFunction :124, recompute() :455: PyLayer that reruns forward in
backward).

TPU-native: jax.checkpoint (rematerialization) over the pure function —
XLA schedules the recompute; semantics (stash RNG, replay with same
dropout) come from jax.checkpoint's deterministic re-trace with the same
key, because our RNG is key-threaded not stateful.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import generator as gen_mod
from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer


def recompute(function, *args, **kwargs):
    """recompute(fn_or_layer, *tensor_args) — gradients recompute the
    forward instead of storing activations."""
    use_reentrant = kwargs.pop("use_reentrant", True)
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)

    fn = function.forward if isinstance(function, Layer) else function

    tensors = []
    spec = []
    for a in args:
        if isinstance(a, Tensor):
            spec.append(("T", len(tensors)))
            tensors.append(a)
        else:
            spec.append(("S", a))

    # capture params referenced by the layer so their grads flow
    params = []
    if isinstance(function, Layer):
        params = [p for p in function.parameters() if not p.stop_gradient]

    key = gen_mod.next_key()

    def pure(arrs_and_params):
        arrs = arrs_and_params[:len(tensors)]
        parrs = arrs_and_params[len(tensors):]
        saved = [(p._data,) for p in params]
        gen = gen_mod.default_generator()
        saved_key, saved_off = gen._key, gen._offset
        try:
            for p, pa in zip(params, parrs):
                p._data = pa
            gen._key, gen._offset = key, 0
            call_args = []
            ai = iter(arrs)
            for kind, v in spec:
                if kind == "T":
                    t = Tensor._wrap(next(ai), stop_gradient=False)
                    call_args.append(t)
                else:
                    call_args.append(v)
            out = fn(*call_args, **kwargs)
            if isinstance(out, Tensor):
                return out._data
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in out)
        finally:
            for p, (pa,) in zip(params, saved):
                p._data = pa
            gen._key, gen._offset = saved_key, saved_off

    ck = jax.checkpoint(pure)

    def f(*arrays):
        return ck(list(arrays))

    outs = run_op("recompute", f, *(tensors + params))
    return outs


def recompute_sequential(ctx, functions, *args):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    fns = list(functions)
    seg = max(1, len(fns) // max(segments, 1))
    out = args
    i = 0
    while i < len(fns):
        chunk = fns[i:i + seg]

        def seg_fn(*xs, _chunk=chunk):
            y = xs
            for f_ in _chunk:
                y = f_(*y) if isinstance(y, tuple) else f_(y)
                if not isinstance(y, tuple):
                    y = (y,)
            return y[0] if len(y) == 1 else y
        out = recompute(seg_fn, *(out if isinstance(out, tuple) else (out,)))
        if not isinstance(out, tuple):
            out = (out,)
        i += seg
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out
