"""Megatron-style sequence parallelism tied to TP.

Reference: fleet/utils/sequence_parallel_utils.py —
ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers (:85-137),
ColumnSequenceParallelLinear (:427) with allgather/GEMM overlap (:255),
RowSequenceParallelLinear, mark_as_sequence_parallel_parameter +
register_sequence_parallel_allreduce_hooks (:192).

TPU-native: between TP blocks, activations are sharded on the *sequence*
dim over the same 'mp' axis the weights use. The Column linear's
"allgather input then GEMM" and the Row linear's "GEMM then
reduce-scatter output" are expressed as sharding constraints; GSPMD
emits the allgather/reduce-scatter pair and overlaps it with the
matmuls (the overlap the reference hand-rolls at :255). The SP-param
grad allreduce hooks (:192) have no analog here: gradients of
replicated params used under sharded activations already come out of
the compiled backward globally reduced.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer
from .mp_layers import _constrain, _mp_axis, _put


def _use_collective_matmul(mesh, axis):
    """Collective matmul is opt-in (FLAGS_collective_matmul or the
    hybrid engine's ParallelConfig.collective_matmul) and needs a real
    mp axis to ring over."""
    if mesh is None or axis is None:
        return False
    if mesh.get_dim_size(axis) <= 1:
        return False
    from paddle_tpu.core.flags import get_flag
    return bool(get_flag("FLAGS_collective_matmul"))


def _seq_spec(ndim, seq_dim=1):
    spec = [None] * ndim
    spec[seq_dim] = "mp"
    return P(*spec)


def scatter(x, seq_dim: int = 1):
    """Replicated -> sequence-sharded over 'mp' (ScatterOp :85)."""
    return run_op("sp_scatter",
                  lambda a: _constrain(a, _seq_spec(a.ndim, seq_dim)), x)


def all_gather(x, seq_dim: int = 1):
    """Sequence-sharded -> replicated (AllGatherOp :107)."""
    return run_op("sp_all_gather",
                  lambda a: _constrain(a, P(*([None] * a.ndim))), x)


def reduce_scatter(x, seq_dim: int = 1):
    """Partial-sum -> sequence-sharded (ReduceScatterOp :127). With
    GSPMD the pending partial-sum never materializes; constraining the
    producer's output to the seq-sharded spec yields a reduce-scatter."""
    return scatter(x, seq_dim)


def mark_as_sequence_parallel_parameter(param):
    """Reference :176 tags params whose grads need the SP allreduce.
    Kept for API parity; the compiled backward already reduces them."""
    param.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_grad=False):
    """Reference :192. No-op on TPU: XLA's partitioner inserts the grad
    reduction for replicated params under sequence-sharded activations."""
    return model


class ColumnSequenceParallelLinear(Layer):
    """Input arrives sequence-sharded [B, S/mp, in]; it is allgathered
    (by constraint) and hit with the column-sharded weight, leaving the
    output TP-sharded on features (reference :427)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = True
        self.bias = self.create_parameter(
            (out_features,), None, is_bias=True) if has_bias else None
        _put(self.weight, P(None, "mp"))
        if self.bias is not None:
            self.bias.is_distributed = True
            _put(self.bias, P("mp"))

    def forward(self, x):
        def f(a, w, *b):
            mesh, axis = _mp_axis()
            if _use_collective_matmul(mesh, axis) and a.ndim == 3:
                # ring-overlapped allgather@W: each scan step multiplies
                # the resident seq shard while the next permutes over
                # ICI (reference sequence_parallel_utils.py:240-340
                # overlap, the TPU way)
                from paddle_tpu.parallel.collective_matmul import \
                    sp_column_matmul
                out = sp_column_matmul(a, w, mesh.jax_mesh, axis)
            else:
                a = _constrain(a, P(*([None] * a.ndim)))  # seq allgather
                out = jnp.matmul(a, w)
            if b:
                out = out + b[0]
            spec = [None] * out.ndim
            if not self.gather_output:
                spec[-1] = "mp"
            return _constrain(out, P(*spec))
        args = (x, self.weight) + ((self.bias,) if self.bias is not None
                                   else ())
        return run_op("column_seq_parallel_linear", f, *args)


class RowSequenceParallelLinear(Layer):
    """Input is TP-sharded on features [B, S, in/mp]; the contraction's
    partial sums are reduce-scattered straight into the sequence-sharded
    output [B, S/mp, out] (reference RowSequenceParallelLinear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = True
        self.bias = self.create_parameter(
            (out_features,), None, is_bias=True) if has_bias else None
        _put(self.weight, P("mp", None))

    def forward(self, x):
        def f(a, w, *b):
            mesh, axis = _mp_axis()
            if _use_collective_matmul(mesh, axis) and a.ndim == 3 and \
                    self.input_is_parallel:
                # X@W -> ring reduce-scatter: the partial-sum tile
                # rotates while the next block computes
                from paddle_tpu.parallel.collective_matmul import \
                    sp_row_matmul
                out = sp_row_matmul(a, w, mesh.jax_mesh, axis)
            else:
                if self.input_is_parallel:
                    a = _constrain(a, P(*([None] * (a.ndim - 1)
                                          + ["mp"])))
                out = jnp.matmul(a, w)
                out = _constrain(out, _seq_spec(out.ndim, 1))  # r-scatter
            if b:
                out = out + b[0]
            return out
        args = (x, self.weight) + ((self.bias,) if self.bias is not None
                                   else ())
        return run_op("row_seq_parallel_linear", f, *args)


GatherOp = all_gather
ScatterOp = scatter
AllGatherOp = all_gather
ReduceScatterOp = reduce_scatter
