"""Hybrid-parallel topology.

Reference: CommunicateTopology (fleet/base/topology.py:70) and
HybridCommunicateGroup (:189) — the N-D rank mesh with axis order
pp → mp → sep → sharding → dp, and per-axis comm groups.

TPU-native: the topology IS a jax.sharding.Mesh with those axis names; a
"comm group" is a mesh axis name (collectives inside jit reference the
axis, not a communicator object). The class keeps the reference's query
surface so Fleet-layer logic carries over.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import numpy as np
import jax

from ..env import Group, get_rank, get_world_size, new_group
from ..mesh import ProcessMesh

_ORDER = ["pp", "sep", "mp", "sharding", "dp"]  # outer→inner device layout


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or _ORDER)
        self._dims = list(dims or [1] * len(self._parallel_names))
        self._world_size = int(np.prod(self._dims))
        self._coord_type = None

    def get_hybrid_group_names(self):
        return list(self._parallel_names)

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = [kwargs[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(coord, self._dims))

    def get_coord(self, rank):
        return tuple(np.unravel_index(rank, self._dims))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = [r for r in range(self._world_size)
                 if self.get_coord(r)[axis] == index]
        return ranks

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: ranks varying on that axis only."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        groups = []
        for other in itertools.product(*[range(d) for d in other_dims]):
            ranks = []
            for i in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, i)
                ranks.append(int(np.ravel_multi_index(coord, self._dims)))
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return int(np.ravel_multi_index(coord, self._dims))


class HybridCommunicateGroup:
    """reference topology.py:189 — holds the mesh + per-axis "groups"."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = get_rank()
        self._dp_degree = topology.get_dim("dp")
        self._mp_degree = topology.get_dim("mp")
        self._pp_degree = topology.get_dim("pp")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") \
            if "sep" in topology.get_hybrid_group_names() else 1
        # the device mesh (single-controller: over local devices)
        n = topology.world_size()
        devs = jax.devices()
        if n > len(devs):
            raise ValueError(
                f"topology needs {n} devices, have {len(devs)}")
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(nm) for nm in names]
        self.mesh = ProcessMesh(shape=dims, dim_names=names,
                                devices=devs[:n])
        my_rank = self.global_rank % n
        coord = self._topo.get_coord(my_rank)
        self._coord = dict(zip(names, coord))
        self._groups: Dict[str, Group] = {}
        for nm in names:
            # the comm group along axis `nm` CONTAINING this process
            comm = next(g for g in self._topo.get_comm_list(nm)
                        if my_rank in g)
            self._groups[nm] = new_group(comm)

    # --- degree queries (reference API) ---
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_data_parallel_rank(self):
        return self._coord.get("dp", 0)

    def get_model_parallel_rank(self):
        return self._coord.get("mp", 0)

    def get_stage_id(self):
        return self._coord.get("pp", 0)

    def get_sharding_parallel_rank(self):
        return self._coord.get("sharding", 0)

    def get_sep_parallel_rank(self):
        return self._coord.get("sep", 0)

    # --- group handles (mesh axis names ride along) ---
    def get_data_parallel_group(self):
        return self._groups.get("dp")

    def get_model_parallel_group(self):
        return self._groups.get("mp")

    def get_pipe_parallel_group(self):
        return self._groups.get("pp")

    def get_sharding_parallel_group(self):
        return self._groups.get("sharding")

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    def get_check_parallel_group(self, *a, **k):
        return self._groups.get("mp")

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    def get_p2p_groups(self):
        return None

    def topology(self):
        return self._topo

    # convenience for TPU code
    @property
    def process_mesh(self) -> ProcessMesh:
        return self.mesh


_HCG: Optional[HybridCommunicateGroup] = None


def get_hybrid_communicate_group():
    return _HCG


def set_hybrid_communicate_group(hcg):
    global _HCG
    _HCG = hcg
    return hcg
