"""fleet.utils namespace (reference
python/paddle/distributed/fleet/utils/__init__.py: exports LocalFS,
HDFSClient, recompute, DistributedInfer plus the helper submodules)."""
from __future__ import annotations

from paddle_tpu.distributed.fleet.recompute import recompute  # noqa: F401

from . import (  # noqa: F401
    fs,
    hybrid_parallel_util,
    log_util,
    mix_precision_utils,
    ps_util,
    timer_helper,
)
from .fs import HDFSClient, LocalFS  # noqa: F401
from .ps_util import DistributedInfer  # noqa: F401

# reference modules that live one level up in this tree, re-exported
# under their reference paths
from paddle_tpu.distributed.fleet import (  # noqa: F401
    pp_parallel_adaptor,
    sequence_parallel_utils,
)

__all__ = ["LocalFS", "recompute", "DistributedInfer", "HDFSClient"]
