"""Filesystem helpers (reference: fleet/utils/fs.py — FS/LocalFS over
python fs ops, HDFSClient over the `hadoop fs` CLI). The checkpoint
paths (framework/io, distributed/checkpoint) accept any FS."""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FSShellCmdAborted(ExecuteError):
    pass


class FS:
    """Abstract FS surface (reference fs.py:72)."""

    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference fs.py:134)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(dst_path)
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def upload(self, local_path, fs_path):
        if local_path != fs_path:
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        if local_path != fs_path:
            shutil.copy(fs_path, local_path)

    def list_dirs(self, fs_path) -> List[str]:
        return self.ls_dir(fs_path)[0]


class HDFSClient(FS):
    """`hadoop fs` CLI wrapper (reference fs.py:474). Commands raise
    ExecuteError when the hadoop client is missing or fails — the
    checkpoint paths fall back to LocalFS on single-host setups."""

    def __init__(self, hadoop_home=None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}
        self._timeout_s = time_out / 1000.0

    def _run(self, *args) -> str:
        cfg = []
        for k, v in self._configs.items():
            cfg += ["-D", f"{k}={v}"]
        cmd = [self._hadoop, "fs"] + cfg + list(args)
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=self._timeout_s)
        except FileNotFoundError as e:
            raise ExecuteError(f"hadoop client not found: {e}") from e
        except subprocess.TimeoutExpired as e:
            raise FSTimeOut(str(e)) from e
        if out.returncode != 0:
            raise ExecuteError(f"{' '.join(cmd)}: {out.stderr[-500:]}")
        return out.stdout

    def ls_dir(self, fs_path):
        lines = self._run("-ls", fs_path).splitlines()
        dirs, files = [], []
        for ln in lines:
            parts = ln.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=True):
        # reference fs.py:1033 — overwrite-delete first, then the
        # existence checks (src must exist, dst must not)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        if test_exists:
            if not self.is_exist(fs_src_path):
                raise FSFileNotExistsError(f"{fs_src_path} is not exists")
            if self.is_exist(fs_dst_path):
                raise FSFileExistsError(f"{fs_dst_path} exists already")
        self._run("-mv", fs_src_path, fs_dst_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if not exist_ok and self.is_exist(fs_path):
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def need_upload_download(self):
        return True
