"""Hybrid-parallel gradient/parameter sync helpers (reference
fleet/utils/hybrid_parallel_util.py).

TPU design: collectives go through paddle_tpu.distributed.collective
(XLA collectives / replicated device_put); "fused" bucketing is kept as
an API but the XLA runtime already coalesces — each call is one
collective per parameter group."""
from __future__ import annotations

from paddle_tpu.distributed import collective as C


def obtain_optimizer_parameters_list(optimizer):
    inner = getattr(optimizer, "_inner_opt", None) or optimizer
    params = getattr(inner, "_parameter_list", None) or []
    if params and isinstance(params[0], dict):
        flat = []
        for group in params:
            flat.extend(group.get("params", []))
        return flat
    return list(params)


def unwrap_optimizer(optimizer, optimizer_instances=()):
    opt = optimizer
    while optimizer_instances and isinstance(opt, optimizer_instances):
        opt = opt._inner_opt
    return opt


def fused_allreduce_gradients_with_group(parameter_list, group,
                                         bucket_size=128 * 1024 * 1024,
                                         scale=None):
    """Sync every present grad over `group`. The reference sums with
    NCCL then divides by nranks; in this single-controller stack the
    collective keeps replicated grads consistent and they are ALREADY
    the global mean (DataParallel.scale_loss), so no implicit divide —
    an explicit `scale` is still honored for callers that pre-scaled."""
    for p in parameter_list:
        def sync(p=p):
            g = getattr(p, "grad", None)
            if g is None:
                return
            C.all_reduce(g, group=group)
            if scale and scale != 1:
                g._assign_array(g._data / scale)
        # keyed by PARAM so an accumulation window (no_sync) records one
        # deferred sync per param that re-reads p.grad at exit — grads
        # are fresh Tensors every backward, so keying by the grad would
        # pin stale arrays and replay k times
        C.defer_or_run(("fused_allreduce", id(p), id(group)), sync)


def fused_allreduce_gradients(parameter_list, hcg):
    group = hcg.get_data_parallel_group() if hcg is not None else None
    fused_allreduce_gradients_with_group(parameter_list, group)


def _broadcast_params(model, group, fuse_params=True):
    for _, p in model.named_parameters():
        C.broadcast(p, src=0, group=group)
    for _, b in model.named_buffers():
        C.broadcast(b, src=0, group=group)


def broadcast_mp_parameters(model, hcg, fuse_params=True):
    _broadcast_params(model, hcg.get_model_parallel_group(), fuse_params)


def broadcast_dp_parameters(model, hcg, fuse_params=True):
    _broadcast_params(model, hcg.get_data_parallel_group(), fuse_params)


def broadcast_sharding_parameters(model, hcg, fuse_params=True):
    _broadcast_params(model, hcg.get_sharding_parallel_group(),
                      fuse_params)


def broadcast_sep_parameters(model, hcg, fuse_params=True):
    _broadcast_params(model, hcg.get_sep_parallel_group(), fuse_params)


def broadcast_input_data(hcg, *inputs, **kwargs):
    """Broadcast batch data across the model-parallel group so every
    TP rank sees identical inputs (reference :168)."""
    group = hcg.get_model_parallel_group()
    from paddle_tpu.core.tensor import Tensor
    out_in = []
    for v in inputs:
        if isinstance(v, Tensor):
            C.broadcast(v, src=0, group=group)
        out_in.append(v)
    for k, v in kwargs.items():
        if isinstance(v, Tensor):
            C.broadcast(v, src=0, group=group)
        kwargs[k] = v
    return out_in, kwargs
