"""Distributed logging helpers (reference fleet/utils/log_util.py)."""
from __future__ import annotations

import logging
import os
import sys

logger = logging.getLogger("paddle_tpu.fleet")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "%(levelname)s %(asctime)s %(name)s: %(message)s"))
    logger.addHandler(_h)
try:
    logger.setLevel(os.environ.get("FLEET_LOG_LEVEL", "INFO").upper())
except ValueError:
    logger.setLevel("INFO")   # bad env value must not break imports


def set_log_level(level):
    """INFO/DEBUG/... by name or logging numeric code."""
    if isinstance(level, str):
        level = level.upper()
    logger.setLevel(level)


def get_log_level_code():
    return logger.getEffectiveLevel()


def get_log_level_name():
    return logging.getLevelName(get_log_level_code())


def layer_to_str(base, *args, **kwargs):
    """Format a layer construction call for debug dumps."""
    parts = [str(a) for a in args]
    parts += [f"{k}={v}" for k, v in kwargs.items()]
    return f"{base}({', '.join(parts)})"
