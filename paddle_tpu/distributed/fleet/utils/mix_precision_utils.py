"""Main-grad mixed-precision wrappers (reference
fleet/utils/mix_precision_utils.py: MixPrecisionLayer keeps an fp32
main_grad per parameter accumulated from the low-precision grads;
MixPrecisionOptimizer steps on the main grads; MixPrecisionScaler
delegates to the wrapped GradScaler).

TPU design: bf16 params + fp32 master weights already live in
paddle_tpu.optimizer (multi_precision); these wrappers add the
main_grad accumulation discipline so hybrid-parallel training can
accumulate micro-batch grads in fp32 exactly like the reference."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer


class MixPrecisionLayer(Layer):
    def __init__(self, layers, dtype="bfloat16"):
        super().__init__()
        self._layers = layers
        self._dtype = dtype
        for _, param in layers.named_parameters():
            param.main_grad = None
            param.register_hook(self._make_accum_hook(param))

    @staticmethod
    def _make_accum_hook(param):
        def hook(grad):
            if grad is None:
                return grad
            g32 = (grad._data if isinstance(grad, Tensor) else grad) \
                .astype(jnp.float32)
            if param.main_grad is None:
                param.main_grad = Tensor._wrap(g32, True)
            else:
                param.main_grad._assign_array(
                    param.main_grad._data + g32)
            return grad
        return hook

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


class MixPrecisionOptimizer:
    """Steps the inner optimizer using each param's fp32 main_grad
    (reference mix_precision_utils.py:97): main_grad is swapped in as
    .grad for the step, then cleared."""

    def __init__(self, optimizer):
        self.__dict__["_inner_opt"] = optimizer

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def _params(self):
        from .hybrid_parallel_util import (
            obtain_optimizer_parameters_list)
        return obtain_optimizer_parameters_list(self._inner_opt)

    def step(self):
        swapped = []
        for p in self._params():
            mg = getattr(p, "main_grad", None)
            if mg is not None:
                swapped.append((p, p.grad))
                p.grad = mg
        self._inner_opt.step()
        for p, old in swapped:
            p.grad = old
            p.main_grad = None

    def clear_grad(self, set_to_zero=True):
        for p in self._params():
            p.main_grad = None
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad


class MixPrecisionScaler:
    """Wraps a GradScaler for main-grad training (reference :244); the
    found-inf scan runs over main_grads via the wrapped scaler."""

    def __init__(self, scaler):
        self.__dict__["_inner_scaler"] = scaler

    def __getattr__(self, name):
        return getattr(self._inner_scaler, name)
