"""PS-mode inference helper (reference fleet/utils/ps_util.py
DistributedInfer: rewrites a program's sparse-embedding lookups into
distributed pull ops against the parameter-server tables).

TPU design: sparse tables live in paddle_tpu.distributed.ps; dense
compute is jitted. DistributedInfer keeps the reference's API: it
binds a ps client and serves embedding pulls for inference loops."""
from __future__ import annotations


class DistributedInfer:
    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program
        self._startup = startup_program
        self._client = None

    def init_distributed_infer_env(self, exe=None, loss=None,
                                   role_maker=None, dirname=None):
        """Connects to the running ps servers (endpoints from the role
        maker env, reference PaddleCloudRoleMaker); dense params are
        expected to be loaded already (dirname accepted for parity)."""
        try:
            from paddle_tpu.distributed.ps import (PaddleCloudRoleMaker,
                                                   PsClient)
            role = role_maker or PaddleCloudRoleMaker()
            eps = role.server_endpoints()
            self._client = PsClient(eps) if eps else None
        except Exception:
            self._client = None
        return self

    def get_dist_infer_program(self):
        """The compiled path needs no program rewrite (embedding pulls
        happen through the ps client at call sites); returns the
        program unchanged, matching the reference's no-sparse-op case."""
        return self._main

    def pull_sparse(self, table_id, ids):
        if self._client is None:
            raise RuntimeError(
                "DistributedInfer: ps client not initialized; call "
                "init_distributed_infer_env() under fleet PS mode")
        return self._client.pull_sparse(table_id, ids)
