"""Named wall-clock timers for train-loop phases (reference
fleet/utils/timer_helper.py: get_timers/set_timers, _Timer, Timers)."""
from __future__ import annotations

import time

_GLOBAL_TIMERS = None


def is_timer_initialized():
    return _GLOBAL_TIMERS is not None


def set_timers():
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS


def get_timers():
    assert _GLOBAL_TIMERS is not None, "timers are not initialized"
    return _GLOBAL_TIMERS


class _Timer:
    def __init__(self, name):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0

    def start(self):
        assert not self.started_, f"timer {self.name} already started"
        self.start_time = time.time()
        self.started_ = True

    def stop(self):
        assert self.started_, f"timer {self.name} is not started"
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset=True):
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names, iteration, normalizer=1.0, reset=False):
        assert normalizer > 0.0
        for name in names:
            if name in self.timers:
                _ = self.timers[name].elapsed(reset=reset) / normalizer

    def log(self, names=None, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                e = self.timers[name].elapsed(reset=reset) / normalizer
                parts.append(f"{name}: {e * 1000.0:.2f}")
        msg = "time (ms) | " + " | ".join(parts)
        from .log_util import logger
        logger.info(msg)
        return msg
