"""Fleet executor: actor-style task runtime (reference:
paddle/fluid/distributed/fleet_executor — Carrier carrier.h:50,
Interceptor interceptor.h:51 message loops, TaskNode task graph, brpc
MessageBus, interceptor_message.proto message types).

TPU framing: the reference uses this actor runtime to drive pipeline
stages as message-passing loops over micro-batches. On TPU the
*device-side* pipeline is a compiled program (collective-permute
schedules in paddle_tpu.distributed.fleet.pp_layers); this module keeps
the actor runtime for what remains host-side work — irregular
orchestration (data pumps, heterogeneous stages, inference DAGs) —
with the same Carrier/Interceptor/TaskNode surface, threads as actors,
and a credit-based DATA_IS_READY / DATA_IS_USELESS flow-control
protocol identical to the reference's compute_interceptor.cc."""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional

# message types (reference interceptor_message.proto:20)
STOP = "STOP"
DATA_IS_READY = "DATA_IS_READY"
DATA_IS_USELESS = "DATA_IS_USELESS"
ERR = "ERR"
RESET = "RESET"
START = "START"


class InterceptorMessage:
    __slots__ = ("src_id", "dst_id", "message_type", "scope_idx",
                 "payload")

    def __init__(self, src_id=0, dst_id=0, message_type=RESET,
                 scope_idx=0, payload=None):
        self.src_id = src_id
        self.dst_id = dst_id
        self.message_type = message_type
        self.scope_idx = scope_idx
        self.payload = payload


class TaskNode:
    """A schedulable unit: runs `program` max_run_times times (one per
    micro-batch) with bounded buffers to up/downstream (reference
    task_node.h)."""

    def __init__(self, rank: int = 0, task_id: int = 0,
                 max_run_times: int = 1, program: Optional[Callable] = None,
                 node_type: str = "Compute"):
        self.rank = rank
        self.task_id = task_id
        self.max_run_times = max_run_times
        self.program = program
        self.node_type = node_type
        self.upstream: Dict[int, int] = {}     # id -> buffer credit
        self.downstream: Dict[int, int] = {}

    def add_upstream_task(self, task_id: int, buffer_size: int = 2):
        self.upstream[task_id] = buffer_size

    def add_downstream_task(self, task_id: int, buffer_size: int = 2):
        self.downstream[task_id] = buffer_size


class Interceptor(threading.Thread):
    """Actor: one thread + one mailbox; subclasses react to messages
    (reference interceptor.h:51 RegisterMsgHandle/LoopOnce)."""

    def __init__(self, interceptor_id: int, node: TaskNode,
                 carrier: "Carrier"):
        super().__init__(daemon=True)
        self.interceptor_id = interceptor_id
        self.node = node
        self.carrier = carrier
        self.mailbox: "queue.Queue[InterceptorMessage]" = queue.Queue()
        self._stopped = False

    def send(self, dst_id: int, msg_type: str, scope_idx=0, payload=None):
        self.carrier.send(InterceptorMessage(
            src_id=self.interceptor_id, dst_id=dst_id,
            message_type=msg_type, scope_idx=scope_idx, payload=payload))

    def enqueue(self, msg: InterceptorMessage):
        self.mailbox.put(msg)

    def run(self):
        while not self._stopped:
            msg = self.mailbox.get()
            if msg.message_type == STOP:
                self._stopped = True
                self.handle_stop(msg)
                break
            try:
                self.handle(msg)
            except Exception as e:  # ERR propagation to carrier
                self.carrier.record_error(self.interceptor_id, e)
                break

    def handle(self, msg: InterceptorMessage):
        raise NotImplementedError

    def handle_stop(self, msg: InterceptorMessage):
        pass


class ComputeInterceptor(Interceptor):
    """Credit-based compute actor (reference compute_interceptor.cc):
    runs when every upstream has data ready and every downstream has
    buffer credit; emits DATA_IS_READY downstream and DATA_IS_USELESS
    upstream after each run."""

    def __init__(self, interceptor_id, node, carrier):
        super().__init__(interceptor_id, node, carrier)
        self._ready: Dict[int, int] = {u: 0 for u in node.upstream}
        self._credit: Dict[int, int] = dict(node.downstream)
        self._pending: Dict[int, List] = {u: [] for u in node.upstream}
        self._run_count = 0

    def _can_run(self):
        ups_ok = all(n > 0 for n in self._ready.values())
        down_ok = all(c > 0 for c in self._credit.values())
        return ups_ok and down_ok and \
            self._run_count < self.node.max_run_times

    def _try_run(self):
        while self._can_run():
            inputs = {u: self._pending[u].pop(0)
                      for u in self._pending if self._pending[u]}
            for u in self._ready:
                self._ready[u] -= 1
            out = None
            if self.node.program is not None:
                out = self.node.program(self._run_count, inputs)
            self._run_count += 1
            for d in self._credit:
                self._credit[d] -= 1
                self.send(d, DATA_IS_READY, scope_idx=self._run_count - 1,
                          payload=out)
            for u in self.node.upstream:
                self.send(u, DATA_IS_USELESS)
            if self._run_count >= self.node.max_run_times:
                self.carrier.notify_done(self.interceptor_id)

    def handle(self, msg: InterceptorMessage):
        if msg.message_type == START:
            self._try_run()
        elif msg.message_type == DATA_IS_READY:
            self._ready[msg.src_id] += 1
            self._pending[msg.src_id].append(msg.payload)
            self._try_run()
        elif msg.message_type == DATA_IS_USELESS:
            self._credit[msg.src_id] += 1
            self._try_run()


class SourceInterceptor(ComputeInterceptor):
    """Head of the DAG: has no upstream; runs on START until its
    micro-batches are exhausted (reference source_interceptor.cc)."""


class SinkInterceptor(ComputeInterceptor):
    """Tail of the DAG (reference sink_interceptor.cc): signals carrier
    completion after the final micro-batch."""


class Carrier:
    """Owns the interceptors of one rank; routes messages; intra-process
    delivery is direct enqueue, cross-carrier via MessageBus (reference
    carrier.h:50)."""

    def __init__(self, rank: int = 0, message_bus: "MessageBus" = None):
        self.rank = rank
        self._interceptors: Dict[int, Interceptor] = {}
        self._bus = message_bus
        self._done = threading.Event()
        self._sinks: List[int] = []
        self._done_count = 0
        self._lock = threading.Lock()
        self._error: Optional[Exception] = None
        if message_bus is not None:
            message_bus.register_carrier(rank, self)

    def set_interceptor(self, interceptor_id: int, icpt: Interceptor):
        self._interceptors[interceptor_id] = icpt

    def add_task_node(self, node: TaskNode,
                      cls=ComputeInterceptor) -> Interceptor:
        icpt = cls(node.task_id, node, self)
        self.set_interceptor(node.task_id, icpt)
        self._sinks.append(node.task_id)   # done = ALL local actors done
        return icpt

    def send(self, msg: InterceptorMessage) -> bool:
        icpt = self._interceptors.get(msg.dst_id)
        if icpt is not None:
            icpt.enqueue(msg)
            return True
        if self._bus is not None:
            return self._bus.send(msg)
        raise KeyError(f"no interceptor {msg.dst_id} and no message bus")

    def enqueue_interceptor_message(self, msg: InterceptorMessage) -> bool:
        return self.send(msg)

    def record_error(self, interceptor_id: int, err: Exception):
        self._error = err
        self._done.set()

    def notify_done(self, interceptor_id: int):
        with self._lock:
            self._done_count += 1
            if self._done_count >= len(self._sinks):
                self._done.set()

    def start(self, timeout: float = 120.0):
        """Kick every interceptor, START the sources, block until all
        sinks finish the final micro-batch (reference Carrier::Start)."""
        self._done.clear()
        self._done_count = 0
        for icpt in self._interceptors.values():
            if not icpt.is_alive():
                icpt.start()
        for icpt in self._interceptors.values():
            if not icpt.node.upstream:
                icpt.enqueue(InterceptorMessage(dst_id=icpt.interceptor_id,
                                                message_type=START))
        if not self._done.wait(timeout):
            raise TimeoutError("fleet executor did not finish")
        if self._error is not None:
            raise self._error

    def stop(self):
        for icpt in self._interceptors.values():
            icpt.enqueue(InterceptorMessage(message_type=STOP))


class MessageBus:
    """Routes messages between carriers (ranks). In-process registry
    here; the reference's brpc bus covers multi-host, which on TPU is
    the coordination-service + compiled-collective path instead
    (SURVEY §2.6)."""

    def __init__(self):
        self._carriers: Dict[int, Carrier] = {}
        self._routes: Dict[int, int] = {}   # interceptor -> rank

    def register_carrier(self, rank: int, carrier: Carrier):
        self._carriers[rank] = carrier

    def register_route(self, interceptor_id: int, rank: int):
        self._routes[interceptor_id] = rank

    def send(self, msg: InterceptorMessage) -> bool:
        rank = self._routes.get(msg.dst_id)
        if rank is None or rank not in self._carriers:
            return False
        carrier = self._carriers[rank]
        icpt = carrier._interceptors.get(msg.dst_id)
        if icpt is None:
            return False
        icpt.enqueue(msg)
        return True


class FleetExecutor:
    """Top-level driver (reference fleet_executor.h): builds one carrier
    per rank from task nodes and runs the DAG."""

    def __init__(self, exe_desc=None):
        self._bus = MessageBus()
        self._carriers: Dict[int, Carrier] = {}

    def carrier(self, rank: int = 0) -> Carrier:
        if rank not in self._carriers:
            self._carriers[rank] = Carrier(rank, self._bus)
        return self._carriers[rank]

    def init(self, rank: int, task_nodes: List[TaskNode]):
        car = self.carrier(rank)
        for node in task_nodes:
            self._bus.register_route(node.task_id, rank)
            car.add_task_node(node)
        return car

    def run(self, timeout: float = 120.0):
        import threading as _t
        threads = []
        for car in self._carriers.values():
            t = _t.Thread(target=car.start, kwargs={"timeout": timeout})
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout)
        for car in self._carriers.values():
            if car._error is not None:
                raise car._error

    def stop(self):
        for car in self._carriers.values():
            car.stop()
