"""paddle.distributed.io (reference: python/paddle/distributed/io.py):
save/load of distributed persistables — single-controller TPU variant
delegates to paddle.save/load on rank 0."""
from __future__ import annotations

import os


def is_persistable(var):
    return getattr(var, "persistable", False)


def save_persistables(executor, dirname, main_program=None, filename=None):
    import paddle_tpu as paddle
    os.makedirs(dirname, exist_ok=True)
    pers = getattr(main_program, "_persistables", {}) if main_program \
        else {}
    paddle.save({k: v for k, v in pers.items()},
                os.path.join(dirname, filename or "persistables.pdparams"))


def load_inference_model_distributed(dirname, executor, **kw):
    raise NotImplementedError("use paddle_tpu.jit.load")
