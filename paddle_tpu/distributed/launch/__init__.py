"""Launcher (reference: python -m paddle.distributed.launch,
launch/main.py:23 — Job/Pod/Container model, HTTP/etcd rendezvous,
log capture).

TPU-native: one controller process per HOST (JAX single-controller owns
all local chips), so --devices fans out to one process per host, not per
chip; rendezvous is the JAX coordination service (rank-0 host:port).
Single-host multi-"rank" CPU simulation is supported for tests via
--nproc_per_node with JAX_PLATFORMS=cpu (the reference's fake-cluster
trick, SURVEY §4.2).
"""
from .main import main  # noqa: F401
