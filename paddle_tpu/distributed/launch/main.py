"""`python -m paddle_tpu.distributed.launch [--opts] script.py args...`"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="rank0 coordinator host:port")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--rank", type=int, default=0,
                   help="this node's rank (multi-host)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes on this host (CPU-sim testing; on TPU "
                        "keep 1 — a single controller drives all chips)")
    p.add_argument("--devices", default=None,
                   help="accepted for reference-CLI parity")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--job_id", default="default")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic relaunch: on worker failure, restart "
                        "the whole job up to N times (reference: "
                        "ElasticManager relaunch / launch controllers' "
                        "replica policy)")
    p.add_argument("--np_range", default=None, metavar="MIN:MAX",
                   help="elastic scale-in/out (reference ElasticManager "
                        "manager.py:125): on worker failure, relaunch at "
                        "the SURVIVING world size (>= MIN) with rewritten "
                        "ranks/endpoints instead of the original np; "
                        "workers resume from their distributed "
                        "checkpoint at the new world size")
    p.add_argument("--elastic_store", default=None,
                   metavar="DIR|tcp://HOST:PORT",
                   help="KV store watched for scale-OUT join "
                        "announcements (the etcd membership dir of the "
                        "reference ElasticManager): a prospective worker "
                        "puts join/<name>; the launcher restarts the job "
                        "at min(MAX, current+joins), and workers resume "
                        "from the distributed checkpoint at the larger "
                        "world size. A plain path selects FileKVStore "
                        "(shared filesystem); tcp://host:port hosts the "
                        "native TCPStore in the launcher (no shared fs — "
                        "the real multi-host deployment shape)")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _spawn(args, attempt, nprocs=None):
    nprocs = nprocs if nprocs is not None else args.nproc_per_node
    world = args.nnodes * nprocs
    master = args.master or "127.0.0.1:8476"
    log_dir = args.log_dir
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    host = master.rsplit(":", 1)[0]
    base_port = int(master.rsplit(":", 1)[1]) + 1
    endpoints = ",".join(f"{host}:{base_port + r}" for r in range(world))
    procs = []
    for local in range(nprocs):
        rank = args.rank * nprocs + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": master,
            "PADDLE_MASTER_ENDPOINT": master,
            "PADDLE_LOCAL_RANK": str(local),
            "PADDLE_JOB_ID": args.job_id,
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT":
                f"{host}:{base_port + rank}",
            "PADDLE_RESTART_COUNT": str(attempt),
        })
        cmd = [sys.executable, args.script] + args.script_args
        stdout = open(os.path.join(
            log_dir, f"worker.{rank}.attempt{attempt}.log"), "w") \
            if log_dir else None
        procs.append((rank, subprocess.Popen(
            cmd, env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None)))
    return procs


def main():
    args = _parse()
    if args.np_range:
        try:
            lo, hi = (int(v) for v in args.np_range.split(":"))
        except ValueError:
            raise SystemExit(
                f"--np_range must be MIN:MAX, got {args.np_range!r}")
        if not (1 <= lo <= hi):
            raise SystemExit(
                f"--np_range needs 1 <= MIN <= MAX, got {args.np_range!r}")
    else:
        lo = hi = None
    if args.elastic_store and not args.np_range:
        raise SystemExit("--elastic_store requires --np_range (the join "
                         "watcher needs a MAX world size to scale to)")
    if args.master is None and args.nnodes == 1:
        # single-host default: an OS-assigned ephemeral port, so
        # concurrent jobs on one machine (e.g. parallel test runs)
        # don't all contend for one fixed port. A small race window
        # remains between releasing the probe socket and the rank-0
        # coordinator binding it.
        args.master = f"127.0.0.1:{_free_port()}"
    attempt = 0            # spawn generation (feeds PADDLE_RESTART_COUNT)
    restarts = 0           # FAILURE relaunches only (gated by
                           # --max_restarts; deliberate scale-out
                           # restarts don't consume the failure budget)
    cur_np = args.nproc_per_node
    store = None
    if args.elastic_store:
        if args.elastic_store.startswith("tcp://"):
            from paddle_tpu.distributed.elastic import TCPKVStore
            hostport = args.elastic_store[6:]
            if ":" not in hostport or \
                    not hostport.rsplit(":", 1)[1].isdigit():
                raise SystemExit(
                    f"--elastic_store {args.elastic_store!r}: expected "
                    "tcp://HOST:PORT with a numeric port")
            host, port = hostport.rsplit(":", 1)
            store = TCPKVStore(host, int(port), is_master=True)
        else:
            from paddle_tpu.distributed.elastic import FileKVStore
            store = FileKVStore(args.elastic_store)
    procs = _spawn(args, attempt)
    code = 0

    def _kill_all(*_):
        for _, p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 5
        for _, p in procs:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()          # reap: no zombies across relaunches

    signal.signal(signal.SIGTERM, lambda *_: (_kill_all(), sys.exit(143)))
    try:
        while procs:
            if store is not None:
                joins = store.get_prefix("join/")
                if joins and cur_np >= hi:
                    # at MAX already: consume the announcements anyway —
                    # left in the store they'd fire a phantom scale-out
                    # right after a later scale-in relaunch
                    for key in joins:
                        store.delete(key)
                    print(f"[launch] ignoring {len(joins)} join(s): "
                          f"already at max world size {hi}",
                          file=sys.stderr)
                elif joins:
                    new_np = min(hi, cur_np + len(joins))
                    print(f"[launch] scaling {cur_np} -> {new_np} "
                          "workers (join)", file=sys.stderr)
                    _kill_all()
                    for key in joins:
                        store.delete(key)
                    attempt += 1
                    cur_np = new_np
                    procs = _spawn(args, attempt, nprocs=cur_np)
                    continue
            alive = []
            failed = None
            for rank, p in procs:
                ret = p.poll()
                if ret is None:
                    alive.append((rank, p))
                elif ret != 0:
                    failed = (rank, ret)
                    break
            if failed is not None:
                rank, ret = failed
                # surviving workers BEFORE teardown (scale-in basis)
                n_alive = sum(1 for _, p in procs if p.poll() is None)
                _kill_all()
                if restarts < args.max_restarts:
                    restarts += 1
                    attempt += 1
                    next_np = cur_np
                    if args.np_range:
                        # ElasticManager scale-in: continue at the
                        # surviving count, clamped to [lo, hi]
                        next_np = max(lo, min(hi, max(n_alive, lo)))
                        if next_np != cur_np:
                            print(f"[launch] scaling {cur_np} -> "
                                  f"{next_np} workers", file=sys.stderr)
                    print(f"[launch] worker {rank} exited with {ret}; "
                          f"relaunching job (attempt {restarts}/"
                          f"{args.max_restarts})", file=sys.stderr)
                    cur_np = next_np
                    procs = _spawn(args, attempt, nprocs=cur_np)
                    continue
                print(f"[launch] worker {rank} exited with {ret}; "
                      "terminating job", file=sys.stderr)
                code = ret
                procs = []
                break
            procs = alive
            time.sleep(0.2)
    except KeyboardInterrupt:
        _kill_all()
        code = 130
    sys.exit(code)


if __name__ == "__main__":
    main()
