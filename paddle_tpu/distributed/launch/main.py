"""`python -m paddle_tpu.distributed.launch [--opts] script.py args...`"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="rank0 coordinator host:port")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--rank", type=int, default=0,
                   help="this node's rank (multi-host)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes on this host (CPU-sim testing; on TPU "
                        "keep 1 — a single controller drives all chips)")
    p.add_argument("--devices", default=None,
                   help="accepted for reference-CLI parity")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--job_id", default="default")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def main():
    args = _parse()
    nprocs = args.nproc_per_node
    world = args.nnodes * nprocs
    master = args.master or "127.0.0.1:8476"
    procs = []
    log_dir = args.log_dir
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    host = master.rsplit(":", 1)[0]
    base_port = int(master.rsplit(":", 1)[1]) + 1
    endpoints = ",".join(f"{host}:{base_port + r}" for r in range(world))
    for local in range(nprocs):
        rank = args.rank * nprocs + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": master,
            "PADDLE_MASTER_ENDPOINT": master,
            "PADDLE_LOCAL_RANK": str(local),
            "PADDLE_JOB_ID": args.job_id,
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT":
                f"{host}:{base_port + rank}",
        })
        cmd = [sys.executable, args.script] + args.script_args
        stdout = open(os.path.join(log_dir, f"worker.{rank}.log"), "w") \
            if log_dir else None
        procs.append((rank, subprocess.Popen(
            cmd, env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None)))
    code = 0

    def _kill_all(*_):
        for _, p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGTERM, _kill_all)
    try:
        while procs:
            alive = []
            for rank, p in procs:
                ret = p.poll()
                if ret is None:
                    alive.append((rank, p))
                elif ret != 0:
                    print(f"[launch] worker {rank} exited with {ret}; "
                          "terminating job", file=sys.stderr)
                    code = ret
                    _kill_all()
                    alive = []
                    break
            procs = alive
            time.sleep(0.2)
    except KeyboardInterrupt:
        _kill_all()
        code = 130
    sys.exit(code)


if __name__ == "__main__":
    main()
