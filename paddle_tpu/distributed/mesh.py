"""Device mesh + placements.

Reference: ProcessMesh (python/paddle/distributed/auto_parallel/
process_mesh.py:85), placements Shard/Replicate/Partial
(phi/core/distributed/auto_parallel/dist_tensor.h + placement_types), and
the hybrid topology axis order pp→mp(tp)→sep→sharding→dp
(fleet/base/topology.py:70).

TPU-native: ProcessMesh IS a jax.sharding.Mesh; placements map to
PartitionSpec dims. XLA/GSPMD then plays the role of the reference's
reshard lattice + per-op SPMD rules (phi/infermeta/spmd_rules).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


# ---------------------------- placements -----------------------------------
class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("R")


class Partial(Placement):
    """Pending-reduction placement. GSPMD has no first-class partial for
    inputs; reshard() materializes it via psum when converting to
    Replicate/Shard (the reference's P→R / P→S reshard functions)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("P", self.reduce_type))


class ReduceType:
    kRedSum = "sum"
    kRedMax = "max"
    kRedMin = "min"
    kRedProd = "prod"
    kRedAvg = "avg"


# ------------------------------- mesh --------------------------------------
_GLOBAL_MESH: Optional["ProcessMesh"] = None


class ProcessMesh:
    """N-d logical device mesh (reference process_mesh.py:85)."""

    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None,
                 shape: Optional[Sequence[int]] = None,
                 process_ids: Optional[Sequence[int]] = None,
                 devices=None):
        if mesh is not None and isinstance(mesh, Mesh):
            self._jax_mesh = mesh
            self._dim_names = list(mesh.axis_names)
            self._shape = list(np.array(mesh.devices).shape)
            return
        if shape is None:
            arr = np.asarray(mesh)
            shape = list(arr.shape)
        else:
            shape = list(shape)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(len(shape))]
        n = int(np.prod(shape))
        devs = list(devices) if devices is not None else jax.devices()[:n]
        if len(devs) < n:
            raise ValueError(
                f"mesh shape {shape} needs {n} devices, only "
                f"{len(devs)} available")
        self._jax_mesh = Mesh(
            np.asarray(devs[:n]).reshape(shape), tuple(dim_names))
        self._dim_names = list(dim_names)
        self._shape = shape

    # reference-compatible surface
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return [d.id for d in self._jax_mesh.devices.flat]

    @property
    def mesh(self):
        return np.asarray(
            [d.id for d in self._jax_mesh.devices.flat]).reshape(self._shape)

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, dim_name, index=None):
        """Sub-mesh along one axis (reference get_mesh_with_dim)."""
        axis = self._dim_names.index(dim_name)
        devs = np.moveaxis(np.asarray(self._jax_mesh.devices), axis, 0)
        if index is not None:
            sub = devs[index]
            names = [n for n in self._dim_names if n != dim_name]
            return ProcessMesh(mesh=Mesh(sub, tuple(names)))
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        return ProcessMesh(mesh=Mesh(devs, tuple(names)))

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            self._shape == other._shape and \
            self._dim_names == other._dim_names

    def __enter__(self):
        global _GLOBAL_MESH
        self._prev = _GLOBAL_MESH
        _GLOBAL_MESH = self
        return self

    def __exit__(self, *exc):
        global _GLOBAL_MESH
        _GLOBAL_MESH = self._prev

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, " \
               f"dim_names={self._dim_names})"


def get_mesh() -> Optional[ProcessMesh]:
    return _GLOBAL_MESH


def set_mesh(mesh: ProcessMesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    return mesh


def auto_mesh(**axis_sizes) -> ProcessMesh:
    """Build a mesh over all local devices, e.g. auto_mesh(dp=2, tp=4)."""
    names = list(axis_sizes)
    shape = [axis_sizes[n] for n in names]
    return ProcessMesh(shape=shape, dim_names=names)


def placements_to_spec(placements: Sequence[Placement],
                       mesh: ProcessMesh, ndim: int) -> PartitionSpec:
    """[Shard(0), Replicate()] over mesh axes -> PartitionSpec per tensor
    dim. placement[i] describes mesh axis i (reference convention)."""
    entries: List[Optional[List[str]]] = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim
            name = mesh.dim_names[axis_idx]
            if entries[d] is None:
                entries[d] = [name]
            else:
                entries[d].append(name)
    spec = [tuple(e) if e and len(e) > 1 else (e[0] if e else None)
            for e in entries]
    return PartitionSpec(*spec)


def spec_to_placements(spec: PartitionSpec, mesh: ProcessMesh,
                       ndim: int) -> List[Placement]:
    placements: List[Placement] = [Replicate()
                                   for _ in range(len(mesh.dim_names))]
    for d, entry in enumerate(tuple(spec) + (None,) * (ndim - len(spec))):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for name in names:
            placements[mesh.dim_names.index(name)] = Shard(d)
    return placements
