"""DataParallel (reference: python/paddle/distributed/parallel.py:219 —
model wrapper + EagerReducer bucketed allreduce, reducer.cc:794).

TPU-native: params are replicated over the 'dp' mesh axis and the input
batch is sharded over it; the gradient allreduce the reference fires from
accumulation-node hooks is inserted by XLA (contraction over the sharded
batch dim → psum onto replicated grads), fused and overlapped by the
compiler — no bucket manager needed.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from .env import init_parallel_env, get_rank, get_world_size  # noqa: F401
from .mesh import ProcessMesh, get_mesh, set_mesh


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, mesh: Optional[ProcessMesh] = None):
        super().__init__()
        self._layers = layers
        mesh = mesh or get_mesh()
        if mesh is None:
            n = len(jax.devices())
            mesh = ProcessMesh(shape=[n], dim_names=["dp"])
            set_mesh(mesh)
        self._mesh = mesh
        # replicate parameters/buffers across the mesh — but leave anything
        # a TP/sharding layer already placed (e.g. mp-sharded weights) alone
        rep = NamedSharding(mesh.jax_mesh, P())
        def _replicate(t):
            sh = getattr(t._data, "sharding", None)
            already_dist = sh is not None and not getattr(
                sh, "is_fully_replicated", True) and len(
                    t._data.devices()) > 1
            if not already_dist:
                t._assign_array(jax.device_put(t._data, rep))
        for _, p in layers.named_parameters():
            _replicate(p)
        for _, b in layers.named_buffers():
            _replicate(b)

    def _shard_input(self, t: Tensor) -> Tensor:
        if not isinstance(t, Tensor) or t.ndim == 0:
            return t
        dp = self._mesh.dim_names[0] if "dp" not in self._mesh.dim_names \
            else "dp"
        if t.shape[0] % self._mesh.get_dim_size(dp) != 0:
            return t
        spec = P(dp, *([None] * (t.ndim - 1)))
        out = Tensor._wrap(
            jax.device_put(t._data, NamedSharding(self._mesh.jax_mesh,
                                                  spec)),
            t.stop_gradient)
        return out

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(x) for x in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss  # mean-reduction over the global batch is already global

    def no_sync(self):
        """Defer gradient synchronization until the context exits
        (reference DataParallel.no_sync, parallel.py:219 area).

        Real effect: every framework-fired grad-sync collective
        (fused_allreduce_gradients, sharding stage-2 grad re-lays,
        user C.all_reduce on grads) inside the context is recorded,
        deduped, and fired ONCE on exit against the accumulated grads.
        Note the GSPMD caveat: reductions XLA embeds inside a compiled
        backward (replicated-param grads over a dp-sharded batch) are
        compiler-owned and not deferrable here — for fully deferred
        compiled accumulation use gradient_merge
        (optimizer.GradientMergeOptimizer / ParallelConfig.
        gradient_merge_steps), where the whole k-step loop is one XLA
        program and the reduction happens once by construction."""
        from . import collective as C
        return C.defer_collectives()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
