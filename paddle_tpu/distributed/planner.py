"""Parallel-plan search over an analytically calibrated cost model.

Reference being re-designed: the auto-parallel static planner
(distributed/auto_parallel/static/planner_v2.py + completion.py) backed
by the measured op table (python/paddle/cost_model/
static_op_benchmark.json). There, a rule-based/ILP planner propagates
dist-attrs and scores programs per-op. TPU-native version: the search
space is the hybrid-parallel config itself — (dp, tp, pp, sp, zero
stage, remat, microbatches) over a chip mesh — and the objective is a
roofline + ring-collective model (cost_model.CostModel) calibrated
against this repo's own recorded bench points (BENCH_r01.json /
NOTES.md), because on TPU the per-op scheduling the reference plans is
owned by XLA; what's left to plan is exactly this config.

Use:
    spec = ModelSpec.gpt(n_params=1.3e9, layers=24, hidden=2048,
                         heads=16, seq=1024, vocab=50257)
    planner = Planner(chip="v5e")
    plans = planner.plan(spec, n_chips=8, global_batch=64)
    best = plans[0]          # -> PlanCandidate(dp=8, zero=1, ...)

`Planner.calibrate(points)` refits the MFU efficiency from measured
(params, tokens/sec/chip) pairs; the default is fit from the round-1
bench records (GPT-1.3B: 14.57k tok/s/chip, GPT-350M-class: 50k —
0.577 / 0.533 MFU on v5e).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from paddle_tpu.cost_model import CostModel, TPU_SPECS

#: per-chip HBM (bytes). Public numbers: v4 32G, v5e 16G, v5p 95G, v6e 32G.
HBM_BYTES = {"v4": 32e9, "v5e": 16e9, "v5p": 95e9, "v6e": 32e9}

@dataclass
class ModelSpec:
    n_params: float
    layers: int
    hidden: int
    heads: int
    seq: int
    vocab: int

    @classmethod
    def gpt(cls, n_params, layers, hidden, heads, seq, vocab):
        return cls(n_params, layers, hidden, heads, seq, vocab)

    @classmethod
    def from_config(cls, cfg):
        """From a models.gpt.GPTConfig-shaped object."""
        h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
        ffn = getattr(cfg, "ffn_mult", 4)
        n = v * h + cfg.max_seq_len * h + L * (
            4 * h * h + 2 * ffn * h * h + 9 * h)
        return cls(float(n), L, h, cfg.num_heads, cfg.max_seq_len, v)


#: calibration points recorded on this repo's own hardware
#: (BENCH_r01.json driver capture + NOTES.md continuation runs); the
#: full spec rides along so calibration charges the same FLOP formula
#: (incl. attention) the estimator uses
_V5E_CALIBRATION = [
    # GPT-1.3B B4 S1024 remat=names fused-CE: 14.57k tok/s/chip
    (ModelSpec.gpt(1.3e9, 24, 2048, 16, 1024, 50257), 14_570.0),
    # 350M-class config: ~50k tok/s/chip
    (ModelSpec.gpt(0.35e9, 24, 1024, 16, 1024, 50257), 50_000.0),
]


@dataclass
class PlanCandidate:
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: bool = False
    zero: int = 0              # 0..3 (sharding stage over dp)
    remat: bool = True
    microbatches: int = 1
    est_step_s: float = math.inf
    est_mem_bytes: float = math.inf
    breakdown: Dict[str, float] = field(default_factory=dict)

    @property
    def collective_matmul(self) -> bool:
        """Ring-overlap knob for the sp matmuls: recommended whenever
        the plan sequence-parallelizes over a real tp axis. At pp==1
        the GSPMD engine runs the ring via a top-level tp shard_map;
        at pp>1 it rides the manual-tp stage body (round 5 —
        models/gpt_manual_tp.py; the nested-region formulation stays
        Shardy-walled, benchmarks/probes/_cm_repro.py). Consumed by
        to_parallel_config()."""
        return self.sp and self.tp > 1

    def to_parallel_config(self, zero_bubble: bool = False,
                           **overrides):
        """Materialize this plan as a hybrid-engine ParallelConfig
        (models/gpt_hybrid.py), carrying the collective_matmul knob and
        the zero/microbatch/remat choices. Extra kwargs override.

        zero_bubble=True upgrades the pipeline schedule to the compiled
        zero-bubble ZBH1; zero_bubble="zbvpp" selects the ZB-V schedule
        (matching Engine.prepare's contract); other strings raise.
        Since round 5 the upgrade applies under tp>1 too (the hybrid
        engine switches to the manual-tp stage body with explicit
        in-branch collectives, models/gpt_manual_tp.py). Preconditions
        the manual-tp body adds beyond 1F1B, checked with clear errors
        at build/trace time: num_heads % tp == 0 (the candidate
        enumerator already guarantees this for planner-built plans) and
        — under sp — seq_len % tp == 0 (the planner cannot know the
        batch shape; pick 1f1b or pad the sequence if your seq length
        does not divide tp). The collective-matmul ring cannot ride the
        cond-gated zero-bubble phases (whole-mesh ppermute), so a
        zero-bubble choice drops it — see the conflict resolution
        below."""
        from paddle_tpu.models.gpt_hybrid import ParallelConfig
        if isinstance(zero_bubble, str) and \
                zero_bubble not in ("zbh1", "zbvpp"):
            raise ValueError(
                f"unrecognized zero_bubble schedule {zero_bubble!r}; "
                "expected True, 'zbh1' or 'zbvpp'")
        zb_sched = zero_bubble if isinstance(zero_bubble, str) else "zbh1"
        sched = "gpipe" if self.pp <= 1 else (
            zb_sched if zero_bubble else "1f1b")
        kw = dict(dp=self.dp, tp=self.tp, pp=self.pp, sp=self.sp,
                  microbatches=self.microbatches,
                  pp_schedule=sched,
                  remat=self.remat, zero1=self.zero >= 1,
                  collective_matmul=self.collective_matmul)
        kw.update(overrides)
        # Resolve knob conflicts AFTER overrides (the final schedule /
        # final fused_ce win; an explicit collective_matmul override is
        # honored as given):
        # - zero-bubble precludes the ring (its cond-gated phases
        #   cannot host the ring's whole-mesh ppermute — gpt_hybrid
        #   _validate_pp_schedule);
        # - at pp>1 the ring rides the manual-tp route, which has no
        #   fused-CE form: with fused_ce on (the default), the fused
        #   CE's memory win outranks the ring overlap, so the ring is
        #   dropped; pass fused_ce=False to take the ring instead.
        if "collective_matmul" not in overrides:
            fce = overrides.get("fused_ce", ParallelConfig.fused_ce)
            if kw["pp_schedule"] in ("zbh1", "zbvpp") or (
                    kw["collective_matmul"] and kw["pp"] > 1 and fce):
                kw["collective_matmul"] = False
        return ParallelConfig(**kw)

    def short(self) -> str:
        return (f"dp{self.dp}xtp{self.tp}xpp{self.pp}"
                f"{'+sp' if self.sp else ''}"
                f"{f'+zero{self.zero}' if self.zero else ''}"
                f"{'' if self.remat else '+noremat'}"
                f"{f'+mb{self.microbatches}' if self.pp > 1 else ''}"
                f"{'+cm' if self.collective_matmul else ''}")


from paddle_tpu.distributed.auto_tuner import _divisors  # noqa: E402


def _spread(vals: List[int], k: int) -> List[int]:
    """Up to k values spanning the range (keep extremes + geometric
    middles) — no silent small-end truncation of the search space."""
    if len(vals) <= k:
        return vals
    idx = sorted({round(i * (len(vals) - 1) / (k - 1))
                  for i in range(k)})
    return [vals[i] for i in idx]


class Planner:
    #: usable fraction of HBM when judging feasibility — the bench
    #: runs within ~5% of HBM (B8 OOMs, B4 fits). Shared with the
    #: auto-tuner's prune_by_planner so the two rules cannot drift.
    hbm_feasible_frac = 0.95

    def __init__(self, chip: str = "v5e", mfu: Optional[float] = None,
                 hbm_bytes: Optional[float] = None,
                 zero_stages: Sequence[int] = (0, 1, 2, 3)):
        """zero_stages limits the ZeRO dimension to what the target
        execution engine implements (the gpt_hybrid compiled engine
        implements stage 1; distributed/sharding.py's group-sharded
        eager path implements 1/2/3) — ranking a plan the target cannot
        execute would hand back an infeasible top-1."""
        self.cm = CostModel(chip)
        self.chip = chip
        self.hbm = hbm_bytes or HBM_BYTES[chip]
        self.zero_stages = tuple(zero_stages)
        self.mfu = mfu if mfu is not None else (
            self.calibrate(_V5E_CALIBRATION) if chip == "v5e"
            else 0.5)

    # ----------------------------------------------------- calibration
    def calibrate(self, points: Sequence[Tuple[ModelSpec, float]]
                  ) -> float:
        """Fit the achieved-MFU efficiency from measured
        (ModelSpec, tokens/sec/chip) pairs using the SAME FLOP formula
        the estimator charges (attention included — double-charging it
        would bias cross-seq ranking); sets and returns self.mfu."""
        effs = []
        for spec, tok_s in points:
            flops_needed = self.cm.train_flops(
                spec.n_params, spec.layers, spec.hidden, spec.seq,
                tok_s)
            effs.append(flops_needed / self.cm.spec["flops"])
        self.mfu = sum(effs) / len(effs)
        return self.mfu

    # ------------------------------------------------------- estimates
    def estimate(self, c: PlanCandidate, m: ModelSpec,
                 global_batch: int) -> PlanCandidate:
        """Fill est_step_s / est_mem_bytes / breakdown for one config."""
        spec = self.cm.spec
        tokens = float(global_batch) * m.seq
        tokens_dp = tokens / c.dp
        bd: Dict[str, float] = {}

        # ---- compute. The calibration points were measured WITH the
        # engine's remat-names policy, so mfu already absorbs its
        # recompute; remat=False removes roughly the re-run forward.
        flops = self.cm.train_flops(m.n_params, m.layers, m.hidden,
                                    m.seq, tokens)
        if not c.remat:
            flops *= 0.9            # names-policy recompute saved
        per_chip_flops = flops / (c.dp * c.tp * c.pp)
        # per-invocation token count: small microbatches leave the MXU
        # under-filled (the measured reason tiny mb configs lose)
        mb_tokens = tokens_dp / max(c.microbatches, 1)
        eff = mb_tokens / (mb_tokens + 512.0)
        bd["compute"] = per_chip_flops / (spec["flops"] * self.mfu * eff)

        # ---- TP activation collectives: per layer, fwd+bwd
        if c.tp > 1:
            act_bytes = 2.0 * tokens_dp * m.hidden
            kind = "reduce_scatter" if c.sp else "all_reduce"
            per_layer = self.cm.collective_cost(kind, act_bytes, c.tp)
            n_coll = 4 * m.layers / c.pp     # 2 fwd + 2 bwd per layer
            bd["tp_comm"] = n_coll * per_layer.time_s
            if c.sp:       # the matching all_gathers
                bd["tp_comm"] += n_coll * self.cm.collective_cost(
                    "all_gather", act_bytes, c.tp).time_s

        # ---- DP gradient + ZeRO parameter traffic
        if c.dp > 1:
            grad_bytes = 4.0 * m.n_params / (c.tp * c.pp)
            bd["dp_comm"] = self.cm.collective_cost(
                "all_reduce", grad_bytes, c.dp).time_s
            if c.zero >= 3:
                # params gathered fwd + bwd
                p_bytes = 2.0 * m.n_params / (c.tp * c.pp)
                bd["dp_comm"] += 2 * self.cm.collective_cost(
                    "all_gather", p_bytes, c.dp).time_s

        # ---- PP: activation hops (fwd + cotangent bwd per microbatch
        # per stage boundary) + the compiled-1F1B ramp bubble.
        # breakdown holds SECONDS only and sums exactly to est_step_s.
        if c.pp > 1:
            hop_bytes = 2.0 * mb_tokens * m.hidden
            bd["pp_comm"] = 2 * c.microbatches * self.cm.collective_cost(
                "ppermute", hop_bytes, c.pp).time_s * (c.pp - 1)
        step = sum(bd.values())
        if c.pp > 1:
            bubble = 2.0 * (c.pp - 1) / max(c.microbatches, 1)
            bd["pp_bubble"] = step * bubble
            step *= (1 + bubble)

        # ---- memory (calibrated against the v5e bench reality:
        # GPT-1.3B B4 S1024 remat=names fits one 16G chip, B8 OOMs)
        shards = c.tp * c.pp
        p_shard = m.n_params / shards
        mem = 2.0 * p_shard                        # bf16 weights
        opt_shard = c.dp if c.zero >= 1 else 1
        mem += 8.0 * p_shard / opt_shard           # f32 adam m+v
        # grads are transient under XLA per-leaf freeing inside the
        # fused update; peak adds ~the largest leaf, not the full tree
        mem += 4.0 * p_shard * 0.1 / (c.dp if c.zero >= 2 else 1)
        if c.zero >= 3:
            mem -= 2.0 * p_shard * (1 - 1.0 / c.dp)  # params dp-sharded
        # activations: saved tensors per layer x tokens on this chip
        # (the remat "names" policy keeps 3: qkv, attn_out, ffn1)
        act_tokens = tokens_dp / (c.tp if c.sp else 1)
        if c.pp > 1:
            act_tokens /= c.microbatches
        act_factor = 3.0 if c.remat else 16.0
        layers_here = m.layers / c.pp
        act = 2.0 * act_tokens * m.hidden * layers_here * act_factor
        if c.pp > 1:
            act *= min(2 * c.pp - 1, c.microbatches)   # 1F1B in-flight
        mem += act

        c.est_step_s = step
        c.est_mem_bytes = mem
        c.breakdown = bd
        return c

    # ----------------------------------------------------------- search
    def refusal_reason(self, m: ModelSpec, n_chips: int,
                       global_batch: int, *, dp: int, tp: int, pp: int,
                       microbatches: int = 1,
                       zero: int = 0) -> Optional[str]:
        """Why a config lies outside candidates()' structural space
        (None = legal). The SINGLE home of the legality rules: both
        candidates() enumeration below and the auto-tuner's
        prune_by_planner answer from here, and the lockstep test
        (test_auto_tuner_telemetry) pins that every enumerated
        candidate passes."""
        if dp * tp * pp != n_chips:
            return "mesh_mismatch"
        if tp > 8:
            return "tp_gt_8"
        if m.heads % tp or m.hidden % tp:
            return "tp_indivisible"
        if m.layers % pp:
            return "pp_indivisible"
        if global_batch % dp:
            return "dp_indivisible"
        if pp == 1:
            if microbatches != 1:
                return "microbatches_without_pp"
        else:
            if microbatches < pp:
                return "microbatches_lt_pp"
            if (global_batch // dp) % microbatches:
                return "microbatches_indivisible"
        if zero > 0 and dp <= 1:
            return "zero_requires_dp"   # zero stages shard over dp
        return None

    def candidates(self, m: ModelSpec, n_chips: int,
                   global_batch: int) -> List[PlanCandidate]:
        out = []
        for tp in _divisors(n_chips):
            if tp > 8 or m.heads % tp != 0 or m.hidden % tp != 0:
                continue
            rest = n_chips // tp
            for pp in _divisors(rest):
                if m.layers % pp != 0:
                    continue
                dp = rest // pp
                if global_batch % dp != 0:
                    continue
                mbs = [mb for mb in _divisors(global_batch // dp)
                       if mb >= pp] if pp > 1 else [1]
                zeros = tuple(z for z in self.zero_stages
                              if z == 0 or dp > 1) or (0,)
                for mb in _spread(mbs, 8):
                    for sp in ({False, tp > 1} if tp > 1 else {False}):
                        for zero in zeros:
                            for remat in (True, False):
                                out.append(PlanCandidate(
                                    dp=dp, tp=tp, pp=pp, sp=sp,
                                    zero=zero, remat=remat,
                                    microbatches=mb))
        return out

    def plan(self, m: ModelSpec, n_chips: int, global_batch: int,
             top_k: int = 5) -> List[PlanCandidate]:
        """Ranked feasible plans (fastest first; memory-infeasible
        configs dropped)."""
        cands = [self.estimate(c, m, global_batch)
                 for c in self.candidates(m, n_chips, global_batch)]
        feasible = [c for c in cands
                    if c.est_mem_bytes <= self.hbm_feasible_frac * self.hbm]
        if not feasible:
            raise RuntimeError(
                f"planner: no feasible config for {m.n_params / 1e9:.1f}B "
                f"params on {n_chips}x{self.chip}")
        # near-equal step times (within 0.5% of the fastest) tie-break
        # toward lower memory — zero stages are free headroom at equal
        # speed; relative bucketing so fast/small workloads don't
        # degenerate to memory-only ranking
        t_min = min(c.est_step_s for c in feasible)
        bucket = max(t_min * 0.005, 1e-9)
        feasible.sort(key=lambda c: (round(c.est_step_s / bucket),
                                     c.est_mem_bytes))
        return feasible[:top_k]

    def throughput(self, c: PlanCandidate, m: ModelSpec,
                   global_batch: int, n_chips: int) -> float:
        """tokens/sec/chip implied by a plan estimate."""
        tokens = global_batch * m.seq
        return tokens / c.est_step_s / n_chips
