"""Parameter-server training runtime (reference:
paddle/fluid/distributed/ps — brpc PS with dense/sparse/geo tables —
and python/paddle/distributed/fleet PS mode).

TPU framing: PS mode serves sparse-dominated workloads (recommender
embeddings) where the embedding table exceeds device memory. The dense
compute path stays on TPU via the normal eager/jit stack; the sparse
path pulls rows into host numpy, feeds them to the device step as
ordinary inputs, and pushes gradients (or Geo deltas) back to host-side
tables. Role topology (server/worker), table sharding by id-hash, and
the a_sync/geo strategy knobs mirror the reference.

Usage (mirrors reference fleet PS flow):
    role = PaddleCloudRoleMaker()          # reads TRAINING_ROLE etc.
    if role.is_server():
        server = PsServer(num_workers=role.worker_num())
        server.run()                       # blocks
    else:
        client = PsClient(role.server_endpoints())
        ...pull/push...
"""
from __future__ import annotations

import os

from .rpc import RpcClient, RpcServer  # noqa: F401
from .server import PsServer  # noqa: F401
from .table import (  # noqa: F401
    DenseTable, SparseGeoTable, SparseTable,
)
from .worker import PsClient  # noqa: F401


class PaddleCloudRoleMaker:
    """Role discovery from env vars (reference
    fleet/base/role_maker.py PaddleCloudRoleMaker):
    TRAINING_ROLE=TRAINER|PSERVER, PADDLE_PSERVERS_IP_PORT_LIST,
    PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID, PADDLE_PORT."""

    def __init__(self, is_collective=False, **kwargs):
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self._servers = [e for e in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
        self._num_workers = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self._worker_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self._port = int(os.environ.get("PADDLE_PORT", 0))

    def is_server(self):
        return self._role == "PSERVER"

    def is_worker(self):
        return self._role == "TRAINER"

    def is_first_worker(self):
        return self.is_worker() and self._worker_id == 0

    def worker_num(self):
        return self._num_workers

    def worker_index(self):
        return self._worker_id

    def server_num(self):
        return len(self._servers)

    def server_endpoints(self):
        return list(self._servers)

    def server_port(self):
        return self._port


class GeoWorker:
    """Geo-SGD async worker (reference GeoSGD: train a local replica,
    push parameter deltas every `trainer_desc.push_step` steps, pull
    fresh global params; memory_sparse_geo_table applies deltas
    additively)."""

    def __init__(self, client: PsClient, table_id: int, dim: int,
                 push_interval: int = 10):
        self._client = client
        self._table_id = table_id
        self._dim = dim
        self._interval = push_interval
        self._step = 0
        self._local = {}       # id -> local row
        self._base = {}        # id -> row at last sync

    def lookup(self, keys):
        """Pull any unseen rows, return the local replica rows."""
        import numpy as np
        keys = np.asarray(keys, np.int64).reshape(-1)
        missing = [k for k in keys.tolist() if k not in self._local]
        if missing:
            rows = self._client.pull_sparse(
                self._table_id, np.asarray(missing, np.int64))
            for k, r in zip(missing, rows):
                self._local[k] = r.copy()
                self._base[k] = r.copy()
        import numpy as _np
        return _np.stack([self._local[int(k)] for k in keys])

    def apply_grads(self, keys, grads, lr):
        import numpy as np
        keys = np.asarray(keys, np.int64).reshape(-1)
        for k, g in zip(keys.tolist(), grads):
            self._local[k] -= lr * g
        self._step += 1
        if self._step % self._interval == 0:
            self.sync()

    def sync(self):
        """Push local deltas; refresh base to the pushed state."""
        import numpy as np
        if not self._local:
            return
        keys = np.asarray(list(self._local), np.int64)
        deltas = np.stack([self._local[int(k)] - self._base[int(k)]
                           for k in keys])
        self._client.push_sparse(self._table_id, keys, deltas)
        rows = self._client.pull_sparse(self._table_id, keys)
        for k, r in zip(keys.tolist(), rows):
            self._local[k] = r.copy()
            self._base[k] = r.copy()


__all__ = [
    "PsServer", "PsClient", "DenseTable", "SparseTable",
    "SparseGeoTable", "PaddleCloudRoleMaker", "GeoWorker", "RpcServer",
    "RpcClient",
]
