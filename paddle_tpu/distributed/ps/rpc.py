"""Length-prefixed pickle RPC for the parameter-server runtime
(reference: the brpc services under
paddle/fluid/distributed/ps/service/ — brpc_ps_server.cc,
brpc_ps_client.cc. The PS data-path lives on host CPUs on both stacks;
here it rides plain sockets with numpy payloads instead of brpc+proto,
and the TPU compute path never touches it)."""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading

_LEN = struct.Struct("!Q")


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class RpcServer:
    """Threaded request/response server: handler(method, kwargs) ->
    result. Runs until .stop()."""

    def __init__(self, host: str, port: int, handler):
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        method, kwargs = _recv_msg(self.request)
                        if method == "__stop__":
                            _send_msg(self.request, ("ok", None))
                            outer._server.shutdown()
                            return
                        try:
                            result = outer._handler(method, kwargs)
                            _send_msg(self.request, ("ok", result))
                        except Exception as e:  # propagate to caller
                            _send_msg(self.request, ("err", repr(e)))
                except (ConnectionError, OSError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._handler = handler
        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def wait(self):
        self._thread.join()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """One persistent connection per endpoint; thread-safe via lock."""

    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=120)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def call(self, method: str, **kwargs):
        with self._lock:
            _send_msg(self._sock, (method, kwargs))
            status, result = _recv_msg(self._sock)
        if status == "err":
            raise RuntimeError(f"ps rpc {method} failed: {result}")
        return result

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
