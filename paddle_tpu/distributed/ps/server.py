"""PS server: owns tables, serves push/pull/barrier (reference:
paddle/fluid/distributed/ps/service/brpc_ps_server.cc +
ps_service/service.cc)."""
from __future__ import annotations

import threading

from .rpc import RpcServer
from .table import DenseTable, SparseGeoTable, SparseTable


class PsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 num_workers: int = 1):
        self._tables = {}
        self._num_workers = num_workers
        self._barrier_lock = threading.Lock()
        self._barrier_cond = threading.Condition(self._barrier_lock)
        self._barrier_count = 0
        self._barrier_gen = 0
        self._rpc = RpcServer(host, port, self._handle)
        self.port = self._rpc.port

    # ------------------------------------------------------------ rpc
    def _handle(self, method, kw):
        return getattr(self, "_rpc_" + method)(**kw)

    def _rpc_create_dense_table(self, table_id, size, optimizer="sgd",
                                **opt_kw):
        if table_id not in self._tables:
            self._tables[table_id] = DenseTable(size, optimizer, **opt_kw)

    def _rpc_create_sparse_table(self, table_id, dim, optimizer="sgd",
                                 geo=False, **opt_kw):
        if table_id not in self._tables:
            cls = SparseGeoTable if geo else SparseTable
            kw = dict(opt_kw)
            if not geo:
                kw["optimizer"] = optimizer
            self._tables[table_id] = cls(dim, **kw)

    def _rpc_pull_dense(self, table_id):
        return self._tables[table_id].pull()

    def _rpc_push_dense(self, table_id, grad):
        self._tables[table_id].push(grad)

    def _rpc_set_dense(self, table_id, values):
        self._tables[table_id].set(values)

    def _rpc_pull_sparse(self, table_id, keys):
        return self._tables[table_id].pull(keys)

    def _rpc_push_sparse(self, table_id, keys, grads):
        self._tables[table_id].push(keys, grads)

    def _rpc_sparse_size(self, table_id):
        return self._tables[table_id].size()

    def _rpc_barrier(self):
        with self._barrier_cond:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cond.notify_all()
            else:
                while gen == self._barrier_gen:
                    self._barrier_cond.wait(timeout=60)

    def _rpc_ping(self):
        return "pong"

    # ------------------------------------------------------- lifecycle
    def start(self):
        self._rpc.start()
        return self

    def run(self):
        """Blocking serve (reference fleet.run_server)."""
        self._rpc.start()
        self._rpc.wait()

    def stop(self):
        self._rpc.stop()
