"""Parameter-server tables (reference:
paddle/fluid/distributed/ps/table/ — memory_dense_table.cc,
memory_sparse_table.cc, memory_sparse_geo_table.cc, accessor.h).

Tables live on the server's host memory as numpy arrays; the optimizer
runs server-side on push (the reference's accessor model). Sparse rows
are created on first access (the reference's on-demand embedding)."""
from __future__ import annotations

import threading
from typing import Dict

import numpy as np


class _SGDRule:
    def __init__(self, lr=1.0):
        self.lr = lr

    def init_state(self, shape):
        return {}

    def update(self, param, grad, state):
        param -= self.lr * grad


class _AdagradRule:
    def __init__(self, lr=0.01, eps=1e-6):
        self.lr = lr
        self.eps = eps

    def init_state(self, shape):
        return {"g2": np.zeros(shape, np.float32)}

    def update(self, param, grad, state):
        state["g2"] += grad * grad
        param -= self.lr * grad / (np.sqrt(state["g2"]) + self.eps)


class _AdamRule:
    def __init__(self, lr=0.001, beta1=0.9, beta2=0.999, eps=1e-8):
        self.lr, self.b1, self.b2, self.eps = lr, beta1, beta2, eps

    def init_state(self, shape):
        return {"m": np.zeros(shape, np.float32),
                "v": np.zeros(shape, np.float32), "t": 0}

    def update(self, param, grad, state):
        state["t"] += 1
        state["m"] = self.b1 * state["m"] + (1 - self.b1) * grad
        state["v"] = self.b2 * state["v"] + (1 - self.b2) * grad * grad
        mh = state["m"] / (1 - self.b1 ** state["t"])
        vh = state["v"] / (1 - self.b2 ** state["t"])
        param -= self.lr * mh / (np.sqrt(vh) + self.eps)


class _SumRule:
    """Geo-SGD accumulation: pushes are deltas, applied directly."""

    def init_state(self, shape):
        return {}

    def update(self, param, grad, state):
        param += grad


_RULES = {"sgd": _SGDRule, "adagrad": _AdagradRule, "adam": _AdamRule,
          "sum": _SumRule}


def make_rule(name: str, **kw):
    return _RULES[name](**kw)


class DenseTable:
    """A contiguous fp32 parameter block (reference
    memory_dense_table.cc)."""

    def __init__(self, size: int, optimizer: str = "sgd", **opt_kw):
        self.data = np.zeros(size, np.float32)
        self._rule = make_rule(optimizer, **opt_kw)
        self._state = self._rule.init_state(size)
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.data.copy()

    def push(self, grad: np.ndarray):
        with self._lock:
            self._rule.update(self.data, grad.astype(np.float32),
                              self._state)

    def set(self, values: np.ndarray):
        with self._lock:
            self.data[...] = values


class SparseTable:
    """id -> fp32[dim] rows, created on first pull (reference
    memory_sparse_table.cc; shard-per-server via the client's id
    routing)."""

    def __init__(self, dim: int, optimizer: str = "sgd",
                 initializer: str = "uniform", init_range: float = 0.05,
                 seed: int = 0, **opt_kw):
        self.dim = dim
        self._rule = make_rule(optimizer, **opt_kw)
        self._rows: Dict[int, np.ndarray] = {}
        self._states: Dict[int, dict] = {}
        self._initializer = initializer
        self._range = init_range
        self._rs = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def _ensure(self, key: int) -> np.ndarray:
        row = self._rows.get(key)
        if row is None:
            if self._initializer == "zeros":
                row = np.zeros(self.dim, np.float32)
            else:
                row = self._rs.uniform(
                    -self._range, self._range, self.dim).astype(np.float32)
            self._rows[key] = row
            self._states[key] = self._rule.init_state(self.dim)
        return row

    def pull(self, keys: np.ndarray) -> np.ndarray:
        with self._lock:
            return np.stack([self._ensure(int(k)) for k in keys]) \
                if len(keys) else np.zeros((0, self.dim), np.float32)

    def push(self, keys: np.ndarray, grads: np.ndarray):
        with self._lock:
            for k, g in zip(keys, grads):
                row = self._ensure(int(k))
                self._rule.update(row, g.astype(np.float32),
                                  self._states[int(k)])

    def size(self) -> int:
        with self._lock:
            return len(self._rows)


class SparseGeoTable(SparseTable):
    """Geo-SGD sparse table: workers train local replicas and push
    parameter DELTAS, applied additively (reference
    memory_sparse_geo_table.cc)."""

    def __init__(self, dim: int, **kw):
        kw.pop("optimizer", None)
        super().__init__(dim, optimizer="sum", **kw)
