"""PS worker client: routes dense blocks round-robin and sparse ids by
hash across servers (reference: brpc_ps_client.cc request routing +
fleet worker init)."""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .rpc import RpcClient


class PsClient:
    def __init__(self, endpoints: Sequence[str]):
        self._clients: List[RpcClient] = [RpcClient(e) for e in endpoints]
        self._n = len(self._clients)

    # ------------------------------------------------------ dense path
    def create_dense_table(self, table_id, size, optimizer="sgd",
                           **opt_kw):
        """Dense block is partitioned contiguously across servers."""
        splits = self._dense_splits(size)
        for c, (lo, hi) in zip(self._clients, splits):
            c.call("create_dense_table", table_id=table_id, size=hi - lo,
                   optimizer=optimizer, **opt_kw)

    def _dense_splits(self, size):
        per = (size + self._n - 1) // self._n
        return [(i * per, min((i + 1) * per, size))
                for i in range(self._n)]

    def pull_dense(self, table_id, size) -> np.ndarray:
        parts = [c.call("pull_dense", table_id=table_id)
                 for c in self._clients]
        return np.concatenate(parts)[:size]

    def push_dense(self, table_id, grad: np.ndarray):
        for c, (lo, hi) in zip(self._clients,
                               self._dense_splits(len(grad))):
            c.call("push_dense", table_id=table_id, grad=grad[lo:hi])

    def set_dense(self, table_id, values: np.ndarray):
        for c, (lo, hi) in zip(self._clients,
                               self._dense_splits(len(values))):
            c.call("set_dense", table_id=table_id, values=values[lo:hi])

    # ----------------------------------------------------- sparse path
    def create_sparse_table(self, table_id, dim, optimizer="sgd",
                            geo=False, **opt_kw):
        for c in self._clients:
            c.call("create_sparse_table", table_id=table_id, dim=dim,
                   optimizer=optimizer, geo=geo, **opt_kw)

    def pull_sparse(self, table_id, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.int64).reshape(-1)
        shard = keys % self._n
        out = None
        for i, c in enumerate(self._clients):
            mask = shard == i
            if not mask.any():
                continue
            rows = c.call("pull_sparse", table_id=table_id,
                          keys=keys[mask])
            if out is None:
                out = np.zeros((len(keys), rows.shape[1]), np.float32)
            out[mask] = rows
        if out is None:
            raise ValueError("pull_sparse with empty keys")
        return out

    def push_sparse(self, table_id, keys: np.ndarray, grads: np.ndarray):
        keys = np.asarray(keys, np.int64).reshape(-1)
        shard = keys % self._n
        for i, c in enumerate(self._clients):
            mask = shard == i
            if mask.any():
                c.call("push_sparse", table_id=table_id, keys=keys[mask],
                       grads=grads[mask])

    def sparse_size(self, table_id) -> int:
        return sum(c.call("sparse_size", table_id=table_id)
                   for c in self._clients)

    # ----------------------------------------------------------- sync
    def barrier(self):
        for c in self._clients:
            c.call("barrier")

    def stop_servers(self):
        for c in self._clients:
            try:
                c.call("__stop__")
            except (RuntimeError, ConnectionError, EOFError):
                pass

    def close(self):
        for c in self._clients:
            c.close()
