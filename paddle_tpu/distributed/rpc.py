"""paddle.distributed.rpc equivalent (reference:
python/paddle/distributed/rpc/rpc.py — init_rpc/rpc_sync/rpc_async over
the C++ brpc agent).

Host-side control-plane RPC between worker processes; rides the same
length-prefixed socket RPC as the parameter server
(distributed/ps/rpc.py). Functions are pickled by fully-qualified name
+ args, executed on the callee's process."""
from __future__ import annotations

import os
import pickle
import threading
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, Optional

from .ps.rpc import RpcClient, RpcServer

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


_state = {"server": None, "workers": {}, "clients": {}, "me": None}
_lock = threading.Lock()


def _handle(method, kw):
    if method == "register":
        _state["workers"][kw["name"]] = WorkerInfo(**kw)
        return {n: vars(w) for n, w in _state["workers"].items()}
    if method == "workers":
        return {n: vars(w) for n, w in _state["workers"].items()}
    if method == "invoke":
        fn = pickle.loads(kw["fn"])
        args = pickle.loads(kw["args"])
        kwargs = pickle.loads(kw["kwargs"])
        return fn(*args, **kwargs)
    raise ValueError(f"unknown rpc method {method}")


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Start this process's RPC service and register with the master
    (rank 0 acts as the registry, the reference's barrier-store role)."""
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", 0))
    world_size = world_size if world_size is not None else int(
        os.environ.get("PADDLE_TRAINERS_NUM", 1))
    master = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:29400")
    host, mport = master.rsplit(":", 1)
    with _lock:
        if rank == 0:
            _state["server"] = RpcServer("0.0.0.0", int(mport),
                                         _handle).start()
            me = WorkerInfo(name, rank, host, int(mport))
            _state["workers"][name] = me
        else:
            _state["server"] = RpcServer("0.0.0.0", 0, _handle).start()
            me = WorkerInfo(name, rank, "127.0.0.1",
                            _state["server"].port)
            c = RpcClient(master)
            infos = c.call("register", **vars(me))
            _state["workers"] = {n: WorkerInfo(**w)
                                 for n, w in infos.items()}
            _state["clients"][master] = c
        _state["me"] = me


def _client_for(to: str) -> RpcClient:
    w = _state["workers"].get(to)
    if w is None:
        # refresh registry from master
        for c in _state["clients"].values():
            infos = c.call("workers")
            _state["workers"] = {n: WorkerInfo(**x)
                                 for n, x in infos.items()}
        w = _state["workers"].get(to)
        if w is None:
            raise ValueError(f"unknown rpc worker {to!r}")
    ep = f"{w.ip}:{w.port}"
    if ep not in _state["clients"]:
        _state["clients"][ep] = RpcClient(ep)
    return _state["clients"][ep]


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=None):
    """Run fn(*args, **kwargs) on worker `to`, return its result
    (reference rpc.py:160)."""
    if _state["me"] is not None and to == _state["me"].name:
        return fn(*(args or ()), **(kwargs or {}))
    c = _client_for(to)
    return c.call("invoke", fn=pickle.dumps(fn),
                  args=pickle.dumps(tuple(args or ())),
                  kwargs=pickle.dumps(dict(kwargs or {})))


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=None):
    """Async variant returning a Future (reference rpc.py:206; the
    reference returns a FutureWrapper with .wait())."""
    fut: Future = Future()

    def run():
        try:
            fut.set_result(rpc_sync(to, fn, args, kwargs, timeout))
        except Exception as e:
            fut.set_exception(e)

    threading.Thread(target=run, daemon=True).start()
    fut.wait = fut.result   # reference API: fut.wait()
    return fut


def get_worker_info(name: str) -> WorkerInfo:
    return _state["workers"][name]


def get_all_worker_infos():
    return list(_state["workers"].values())


def shutdown():
    with _lock:
        for c in _state["clients"].values():
            c.close()
        _state["clients"].clear()
        if _state["server"] is not None:
            _state["server"].stop()
            _state["server"] = None
        _state["workers"].clear()
        _state["me"] = None
