"""Group-sharded data parallelism (ZeRO stages 1/2/3) — manual fleet API.

Reference surface being provided (SURVEY §2.7 sharding rows):
  - paddle.distributed.sharding.group_sharded_parallel
    (python/paddle/distributed/sharding/group_sharded.py)
  - DygraphShardingOptimizer
    (fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:48)
  - GroupShardedStage2 (fleet/meta_parallel/sharding/group_sharded_stage2.py:46)
  - GroupShardedStage3 (group_sharded_stage3.py:85)
  - save_group_sharded_model

TPU-native design — the reference's machinery maps onto GSPMD shardings
instead of streams/buckets:

  stage 1 (os):    optimizer states live dp/sharding-axis sharded; the
                   rank-local update + param broadcast the reference does
                   by hand is XLA's sharded-update + allgather.
  stage 2 (os_g):  + gradients are *stored* sharded. The reference
                   reduce-scatters grads into rank slices from backward
                   hooks; here a post-accumulation hook re-lays the
                   accumulated grad onto the sharded spec, so XLA keeps
                   only the local slice (under jit the sharding
                   constraint makes the psum a reduce-scatter).
  stage 3 (p_g_os): + parameters themselves sharded. The reference
                   allgathers params pre-forward and releases them
                   post-backward with stream events; GSPMD inserts the
                   allgather at each use point and its DCE releases the
                   gathered copy — same memory shape, no hand scheduling.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from .mesh import ProcessMesh, get_mesh, set_mesh


def _resolve_axis(group=None):
    """(mesh, axis_name) for the sharding group: the fleet 'sharding'
    axis when present, else 'dp', else first axis of a 1-axis mesh over
    all devices."""
    mesh = getattr(group, "process_mesh", None) or get_mesh()
    if mesh is None:
        n = len(jax.devices())
        mesh = ProcessMesh(shape=[n], dim_names=["dp"])
        set_mesh(mesh)
    for name in ("sharding", "dp"):
        if name in mesh.dim_names and mesh.get_dim_size(name) > 1:
            return mesh, name
    return mesh, mesh.dim_names[0]


def _shard_spec(shape, mesh, axis):
    """PartitionSpec sharding the largest divisible dim over `axis`,
    or None if nothing divides (small tensors stay replicated)."""
    n = mesh.get_dim_size(axis)
    if not shape or n <= 1:
        return None
    order = sorted(range(len(shape)), key=lambda d: -shape[d])
    for dim in order:
        if shape[dim] % n == 0 and shape[dim] >= n:
            spec = [None] * len(shape)
            spec[dim] = axis
            return PartitionSpec(*spec)
    return None


class DygraphShardingOptimizer:
    """Stage-1 sharded optimizer (reference
    dygraph_sharding_optimizer.py:48). Accumulators are created lazily by
    the inner optimizer; after each step's creation they are re-laid
    sharded over the group axis so each rank stores 1/N of the optimizer
    state. Master weights (AMP O2) follow the same placement."""

    def __init__(self, optimizer, hcg=None, group=None):
        self._inner_opt = optimizer
        self._mesh, self._axis = _resolve_axis(group)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def _shard_states(self):
        mesh = self._mesh
        for _, d in getattr(self._inner_opt, "_accumulators", {}).items():
            for _, acc in d.items():
                spec = _shard_spec(acc._data.shape, mesh, self._axis)
                if spec is None:
                    continue
                sh = NamedSharding(mesh.jax_mesh, spec)
                if getattr(acc._data, "sharding", None) != sh:
                    acc._data = jax.device_put(acc._data, sh)
        mw = getattr(self._inner_opt, "_master_weights", None)
        if isinstance(mw, dict):
            for _, w in mw.items():
                spec = _shard_spec(w._data.shape, mesh, self._axis)
                if spec is not None:
                    sh = NamedSharding(mesh.jax_mesh, spec)
                    if getattr(w._data, "sharding", None) != sh:
                        w._data = jax.device_put(w._data, sh)

    def step(self):
        if hasattr(self._inner_opt, "_create_accumulators"):
            self._inner_opt._create_accumulators()
        self._shard_states()
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)


class GroupShardedStage2(Layer):
    """Stage-2 wrapper (reference group_sharded_stage2.py:46): gradients
    are stored group-axis-sharded. A post-accumulation hook on every
    trainable param re-lays `param.grad` onto the sharded spec the moment
    backward finishes accumulating it, releasing the replicated copy —
    the reduce-scatter the reference fires from its grad hooks."""

    def __init__(self, layer: Layer, optimizer=None, group=None,
                 sync_buffers=False, buffer_max_size=2 ** 23,
                 auto_refresh_trainable=True, device="tpu",
                 dp_group=None):
        super().__init__()
        self._layers = layer
        self._mesh, self._axis = _resolve_axis(group)
        for _, p in layer.named_parameters():
            if p.stop_gradient:
                continue
            p._register_backward_hook(self._reshard_grad)

    def _reshard_grad(self, leaf: Tensor):
        from . import collective as C

        def relay():
            g = leaf.grad
            if g is None:
                return
            spec = _shard_spec(g._data.shape, self._mesh, self._axis)
            if spec is None:
                return
            sh = NamedSharding(self._mesh.jax_mesh, spec)
            if getattr(g._data, "sharding", None) != sh:
                g._data = jax.device_put(g._data, sh)
        # under no_sync the re-lay (the stage-2 reduce-scatter analog)
        # is deferred to the context exit — one re-lay per param per
        # accumulation window instead of one per microbatch
        C.defer_or_run(("stage2_relay", id(leaf)), relay)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def get_all_parameters(self):
        """Reference API: materialize full (replicated) params."""
        rep = NamedSharding(self._mesh.jax_mesh, PartitionSpec())
        for _, p in self._layers.named_parameters():
            p._assign_array(jax.device_put(p._data, rep))


class GroupShardedStage3(GroupShardedStage2):
    """Stage-3 wrapper (reference group_sharded_stage3.py:85): parameters
    sharded over the group axis at wrap time. XLA allgathers each param
    at its use point inside the compiled step (the reference's pre-forward
    allgather) and frees the gathered buffer after last use (the
    reference's post-backward release)."""

    def __init__(self, layer: Layer, optimizer=None, group=None,
                 sync_buffers=False, device="tpu", segment_size=2 ** 20,
                 pertrain_sync_models=True, offload=False, sync_comm=False,
                 dp_group=None, exclude_layer=None):
        super().__init__(layer, optimizer=optimizer, group=group,
                         sync_buffers=sync_buffers, dp_group=dp_group)
        for _, p in layer.named_parameters():
            spec = _shard_spec(p._data.shape, self._mesh, self._axis)
            if spec is None:
                continue
            p._assign_array(jax.device_put(
                p._data, NamedSharding(self._mesh.jax_mesh, spec)))


class GroupShardedScaler:
    """Reference group_sharded_utils.GroupShardedScaler: wraps GradScaler.
    The cross-rank found_inf allreduce it adds is unnecessary here — the
    finite-check reduction runs over the (sharded) global grads inside
    XLA, which emits the collective."""

    def __init__(self, scaler):
        self._scaler = scaler

    def __getattr__(self, item):
        return getattr(self._scaler, item)


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """reference group_sharded.py:33 — wrap (model, optimizer, scaler)
    for ZeRO level 'os' | 'os_g' | 'p_g_os'."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be one of 'os', 'os_g', 'p_g_os'")
    optimizer = DygraphShardingOptimizer(optimizer, group=group)
    if level == "os_g":
        model = GroupShardedStage2(model, optimizer, group=group,
                                   sync_buffers=sync_buffers,
                                   buffer_max_size=buffer_max_size,
                                   dp_group=dp_group)
    elif level == "p_g_os":
        model = GroupShardedStage3(model, optimizer, group=group,
                                   sync_buffers=sync_buffers,
                                   segment_size=segment_size,
                                   offload=offload, sync_comm=sync_comm,
                                   dp_group=dp_group,
                                   exclude_layer=exclude_layer)
    if scaler is not None:
        scaler = GroupShardedScaler(scaler)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """reference group_sharded.py save_group_sharded_model: gather the
    sharded params to full tensors and save a plain state_dict."""
    import os

    from paddle_tpu.framework import io as fio

    if isinstance(model, GroupShardedStage2):
        inner, mesh = model._layers, model._mesh
    else:
        inner, mesh = model, get_mesh()
    state = {}
    for name, p in inner.state_dict().items():
        arr = p._data if isinstance(p, Tensor) else p
        if getattr(arr, "sharding", None) is not None:
            if mesh is not None:
                arr = jax.device_put(
                    arr, NamedSharding(mesh.jax_mesh, PartitionSpec()))
            else:
                arr = jax.numpy.asarray(np.asarray(arr))
        state[name] = Tensor._wrap(arr, True) if not isinstance(p, Tensor) \
            else Tensor._wrap(arr, p.stop_gradient)
    os.makedirs(output, exist_ok=True)
    fio.save(state, os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        fio.save(optimizer.state_dict(),
                 os.path.join(output, "model.pdopt"))
