"""paddle.distributed.utils equivalent — MoE comm ops
(reference: distributed/utils/moe_utils.py global_scatter/global_gather
over NCCL all-to-all; here: jnp reshuffles eagerly, lax all_to_all
under jit over the ICI mesh)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["global_scatter", "global_gather"]


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """Dispatch rows of x to experts across ranks (reference
    moe_utils.py global_scatter). Single-controller eager semantics:
    rows are reordered into expert-major layout; under pjit the same
    pattern becomes lax.all_to_all over the expert axis."""
    def f(a, lc, gc):
        order = jnp.argsort(jnp.repeat(
            jnp.arange(lc.shape[0]), lc.astype(jnp.int32),
            total_repeat_length=a.shape[0]), stable=True)
        return jnp.take(a, order, axis=0)
    return run_op("global_scatter", f, x, local_count, global_count)


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter (reference moe_utils.py
    global_gather)."""
    def f(a, lc, gc):
        ids = jnp.repeat(jnp.arange(lc.shape[0]), lc.astype(jnp.int32),
                         total_repeat_length=a.shape[0])
        order = jnp.argsort(ids, stable=True)
        inv = jnp.zeros_like(order)
        inv = inv.at[order].set(jnp.arange(order.shape[0]))
        return jnp.take(a, inv, axis=0)
    return run_op("global_gather", f, x, local_count, global_count)
