"""Collective watchdog (reference: phi/core/distributed/
comm_task_manager.cc + nccl_comm_task.cc — records start/end of
collectives, detects hangs, dumps per-rank state).

TPU-native: XLA collectives can't be individually instrumented from
Python, so the watchdog monitors *device progress*: a heartbeat thread
issues a tiny probe computation every interval; if the device fails to
complete it within FLAGS_collective_timeout_s (a hung ICI collective /
dead coordinator blocks the stream), the watchdog dumps state and invokes
the timeout callback.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, Optional

import jax

from paddle_tpu.core.flags import get_flag
from paddle_tpu.observability import metrics as _met


class TrainHangError(RuntimeError):
    """A train step stalled past the watchdog timeout and the loop was
    aborted with a straggler report — the alternative was a silent
    hang. ``stragglers`` carries the ranks the cross-rank progress
    exchange named (None when no store was configured)."""

    def __init__(self, msg, stragglers=None):
        super().__init__(msg)
        self.stragglers = stragglers


def _record_trip(stragglers):
    """Cataloged metrics for a watchdog trip: dashboards and the
    elastic supervisor must see hang aborts without parsing stdout."""
    if _met._ENABLED:
        _met.REGISTRY.counter("train.hang_aborts").inc()
        _met.REGISTRY.gauge("train.straggler_ranks").set(
            len(stragglers or ()))


class CollectiveWatchdog:
    """Device-progress watchdog with cross-rank attribution.

    When `store` (or FLAGS_watchdog_store_root) is set, every rank
    publishes its progress — wall time of the last successful probe and
    an op counter from the dispatch layer — under
    ``watchdog/{job}/{rank}``. On a local timeout the dump reads every
    rank's published progress and names the straggler(s): ranks whose
    last heartbeat is older than the timeout (or missing entirely) —
    the role of the reference's comm_task_manager per-collective
    start/end records (comm_task_manager.cc), re-based on progress
    heartbeats because XLA collectives cannot be individually
    instrumented from Python."""

    def __init__(self, timeout_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 on_timeout: Optional[Callable] = None,
                 store=None, job_id: str = "default",
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None):
        self.timeout_s = timeout_s if timeout_s is not None else \
            get_flag("FLAGS_collective_timeout_s")
        self.interval_s = interval_s if interval_s is not None else \
            get_flag("FLAGS_watchdog_interval_s")
        self.on_timeout = on_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_ok = time.monotonic()
        self.tripped = False
        self.stragglers: Optional[list] = None
        self.job_id = job_id
        # rank/world resolved lazily: touching jax.process_index here
        # would force backend init for the common store-less watchdog
        self._rank = rank
        self._world_size = world_size
        if store is None:
            root = get_flag("FLAGS_watchdog_store_root")
            if root:
                from .elastic import FileKVStore
                store = FileKVStore(root)
        self.store = store
        self._op_count = 0
        self._unobserve = None
        if self.store is not None:
            from paddle_tpu.core import dispatch as _dispatch

            def _count(name, outs):
                self._op_count += 1
            self._unobserve = _dispatch.add_op_observer(_count)

    @property
    def rank(self):
        if self._rank is None:
            try:
                self._rank = jax.process_index()
            except Exception:
                self._rank = 0
        return self._rank

    @property
    def world_size(self):
        if self._world_size is None:
            try:
                self._world_size = jax.process_count()
            except Exception:
                pass
        return self._world_size

    def _publish(self):
        if self.store is None:
            return
        import json
        self.store.put(
            f"watchdog/{self.job_id}/{self.rank}",
            json.dumps({"ts": time.time(), "ops": self._op_count}))

    def _read_peers(self):
        if self.store is None:
            return {}
        import json
        out = {}
        for k, v in self.store.get_prefix(
                f"watchdog/{self.job_id}/").items():
            try:
                out[int(k.rsplit("/", 1)[-1])] = json.loads(v)
            except (ValueError, TypeError):
                pass
        return out

    def find_stragglers(self):
        """Ranks whose last published heartbeat is older than the
        timeout relative to the freshest rank, PLUS ranks that never
        published at all (expected via world_size — a peer that died
        before its first heartbeat must still be named)."""
        peers = self._read_peers()
        if not peers:
            return None
        newest = max(p["ts"] for p in peers.values())
        stale = [r for r, p in peers.items()
                 if newest - p["ts"] > min(self.timeout_s,
                                           2 * self.interval_s + 1.0)]
        missing = []
        if self.world_size:
            missing = [r for r in range(self.world_size)
                       if r not in peers]
        return sorted(set(stale) | set(missing))

    def _print_peer_report(self, empty_msg=None):
        """Per-rank progress block shared by every trip dump (one
        format for log scrapers to key on). ``empty_msg`` overrides
        the no-straggler verdict line (the step watchdog distinguishes
        all-ranks-stalled from all-ranks-fresh)."""
        if self.stragglers is None:
            return
        peers = self._read_peers()
        now = time.time()
        print("per-rank progress (published heartbeats):")
        for r in sorted(peers):
            p = peers[r]
            tag = "  <-- STRAGGLER" if r in self.stragglers else ""
            print(f"  rank {r}: ops={p.get('ops')} "
                  f"last_heartbeat={now - p['ts']:.1f}s ago{tag}")
        missing = [r for r in self.stragglers if r not in peers]
        if missing:
            print(f"  never published: rank(s) {missing}")
        if self.stragglers:
            print(f"suspected straggler rank(s): {self.stragglers}")
        else:
            print(empty_msg or
                  "all ranks show fresh heartbeats — suspect the "
                  "local device/runtime, not a peer")

    def _probe_once(self) -> bool:
        done = threading.Event()

        def work():
            try:
                import jax.numpy as jnp
                (jnp.zeros(()) + 1).block_until_ready()
                done.set()
            except Exception:
                pass

        t = threading.Thread(target=work, daemon=True)
        t.start()
        return done.wait(self.timeout_s)

    def _loop(self):
        try:
            self._publish()
            while not self._stop.wait(self.interval_s):
                if self._probe_once():
                    self.last_ok = time.monotonic()
                    self._publish()
                else:
                    self.tripped = True
                    self._dump()
                    _record_trip(self.stragglers)
                    if self.on_timeout is not None:
                        self.on_timeout(self)
                    return
        finally:
            # allow a later start() to re-arm monitoring after a trip
            self._thread = None

    def _dump(self):
        print("=" * 60)
        print("[collective watchdog] device probe timed out after "
              f"{self.timeout_s}s — possible hung collective / dead "
              "coordination service")
        try:
            print("process_index:", jax.process_index(),
                  "device_count:", len(jax.devices()))
        except Exception:
            pass
        self.stragglers = self.find_stragglers()
        self._print_peer_report()
        dump_path = get_flag("FLAGS_memory_stats_dump_path")
        if dump_path:
            try:
                from paddle_tpu import device as _device
                _device.dump_memory_stats(dump_path)
                print(f"memory stats dumped to {dump_path}")
            except Exception:
                pass
        print("live python threads:")
        for tid, frame in sys_frames():
            print(f"  thread {tid}:")
            print("   " + "   ".join(traceback.format_stack(frame)[-3:]))
        print("=" * 60)

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
        if self._unobserve is not None:
            self._unobserve()
            self._unobserve = None


class TrainStepWatchdog(CollectiveWatchdog):
    """Per-step stall watchdog for the train loop (ISSUE 15).

    The collective watchdog above monitors *device* progress; a train
    step can also stall with a healthy device — a hung host collective
    rendezvous, a wedged data pipeline, a peer stuck pre-dispatch.
    This variant is armed per step (``step_begin``/``step_end``, or
    the ``step()`` context manager): a monitor thread trips when the
    armed step exceeds ``timeout_s`` (default
    ``FLAGS_step_timeout_s``), publishes/reads cross-rank progress to
    name the straggler(s), ticks ``train.hang_aborts`` /
    ``train.straggler_ranks``, and ABORTS — by ``on_timeout`` when
    given, else by interrupting the main thread, which the hapi/fleet
    train loops translate into :class:`TrainHangError` carrying the
    straggler report. A step that never ends is never a silent hang.

    Lifecycle: the watchdog is caller-owned (one instance can span
    many fits). The monitor thread hibernates after ~_IDLE_EXIT_TICKS
    disarmed intervals and restarts on the next arm; ``stop()``
    releases it immediately and unregisters the store-mode dispatch
    observer.
    """

    def __init__(self, timeout_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 on_timeout: Optional[Callable] = None, **kw):
        if timeout_s is None:
            timeout_s = get_flag("FLAGS_step_timeout_s")
        if interval_s is None:
            interval_s = max(0.01, min(
                get_flag("FLAGS_watchdog_interval_s"), timeout_s / 4.0))
        super().__init__(timeout_s=timeout_s, interval_s=interval_s,
                         on_timeout=on_timeout, **kw)
        self._armed_at: Optional[float] = None
        self._armed_step = None
        self._last_publish = 0.0
        #: serializes arm/spawn against the monitor's idle-exit: an
        #: armed step must NEVER be left unmonitored by a hibernation
        #: racing a re-arm
        self._monitor_lock = threading.Lock()
        #: the abort token: set when the monitor SENDS the interrupt,
        #: consumed exactly once by the train loop's translation —
        #: keyed on the abort itself, not on trip state, so a
        #: late-landing SIGINT is still translated and a genuine
        #: ctrl-C never is
        self._abort_error: Optional[TrainHangError] = None
        self._abort_sent_at = 0.0
        #: trip-time classification: True when every rank's progress
        #: stalled at the same step (a wedged collective), False when
        #: peers look fresh (suspect the local step)
        self.collective_suspect = False

    # ------------------------------------------------------ arm / disarm
    def step_begin(self, step=None):
        if self.on_timeout is None and threading.current_thread() \
                is not threading.main_thread():
            # CPython delivers KeyboardInterrupt only in the MAIN
            # thread: the default abort can neither interrupt a
            # worker-thread step (silent hang persists) nor avoid
            # killing unrelated main-thread work — refuse up front
            raise RuntimeError(
                "TrainStepWatchdog's default abort interrupts the "
                "main thread; arming from a worker thread requires "
                "on_timeout= (e.g. lambda wd: os._exit(17), or a "
                "custom abort channel)")
        self._armed_step = step
        # a new arm clears the previous trip's REPORT state (the abort
        # token above is what the loops translate on, so clearing here
        # cannot rebrand or drop an in-flight abort)
        self.tripped = False
        self.stragglers = None
        self.collective_suspect = False
        with self._monitor_lock:
            self._armed_at = time.monotonic()
            self.start()        # monitor auto-starts on first arm
        self._publish_throttled()
        return self

    def step_end(self):
        self._armed_at = None
        self.last_ok = time.monotonic()
        self._publish_throttled()

    def _publish_throttled(self):
        """At most one store publish per interval_s: step boundaries
        fire every few ms on fast steps, and two blocking shared-fs
        writes per step per rank would tax the hot path for freshness
        the straggler heuristic (threshold ~2*interval_s) can't even
        observe."""
        if self.store is None:
            return
        now = time.monotonic()
        if now - self._last_publish >= self.interval_s:
            self._last_publish = now
            self._publish()

    def step(self, step=None):
        """Context manager arming the watchdog around one step."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            self.step_begin(step)
            try:
                yield self
            finally:
                self.step_end()
        return _cm()

    def hang_error(self) -> TrainHangError:
        msg = (f"train step {self._armed_step} stalled for more than "
               f"{self.timeout_s}s — aborted by the step watchdog "
               "instead of hanging silently")
        if self.stragglers:
            msg += f"; suspected straggler rank(s): {self.stragglers}"
        elif self.collective_suspect:
            # heartbeats refresh at STEP boundaries here; when every
            # rank's last beat predates this step's arm and none lags
            # the rest, the whole job blocked at the same step —
            # blaming "the local pipeline" would misdirect the
            # operator in the flagship multi-rank-hang scenario
            msg += ("; every rank's progress stalled at the same "
                    "step — suspect a wedged collective/coordination "
                    "service, not a single peer or the local data "
                    "pipeline")
        elif self.stragglers is not None:
            msg += ("; peer ranks show fresh progress — suspect "
                    "the local step (data pipeline / host code), not "
                    "a peer")
        return TrainHangError(msg, self.stragglers)

    def consume_abort(self) -> Optional[TrainHangError]:
        """The abort token, exactly once. The train loops call this
        from their ``except KeyboardInterrupt`` to decide whether the
        interrupt is the watchdog's (translate to the stored
        TrainHangError) or the operator's (propagate). Tokens expire
        after 30s so an abort swallowed by foreign code can never
        rebrand a much-later genuine ctrl-C."""
        err, self._abort_error = self._abort_error, None
        if err is not None and \
                time.monotonic() - self._abort_sent_at < 30.0:
            return err
        return None

    # ---------------------------------------------------------- monitor
    #: disarmed ticks before the monitor thread hibernates (the next
    #: step_begin restarts it) — a finished training run must not leak
    #: a polling thread for the process lifetime
    _IDLE_EXIT_TICKS = 25

    def _loop(self):
        idle = 0
        try:
            self._publish_throttled()
            while not self._stop.wait(self.interval_s):
                t0 = self._armed_at
                if t0 is None:
                    idle += 1
                    if idle >= self._IDLE_EXIT_TICKS:
                        # hibernate — but the exit decision and the
                        # thread-slot release must be ATOMIC against a
                        # concurrent step_begin, or its start() no-ops
                        # on our dying thread and the armed step runs
                        # unmonitored
                        with self._monitor_lock:
                            if self._armed_at is not None:
                                idle = 0
                                continue
                            self._thread = None
                            return
                    continue
                idle = 0
                if time.monotonic() - t0 <= self.timeout_s:
                    continue
                # evidence-gathering (store reads) and the report dump
                # are slow; the step may complete meanwhile. Re-check
                # that THIS arm (!= catches a completed step whose
                # successor re-armed during the dump) is still active
                # before declaring a trip or firing the abort —
                # on_timeout is documented as os._exit territory and
                # must never kill a run whose step just finished.
                stragglers = self.find_stragglers()
                if self._armed_at != t0:
                    continue
                peers = self._read_peers()
                now = time.time()
                armed_for = time.monotonic() - t0
                self.collective_suspect = (
                    len(peers) > 1 and not stragglers and all(
                        now - p["ts"] >= armed_for - 2 * self.interval_s
                        for p in peers.values()))
                self.stragglers = stragglers
                self._dump_step()
                if self._armed_at != t0:
                    continue      # completed during the dump: report
                                  # printed, healthy loop NOT aborted
                self.tripped = True
                _record_trip(stragglers)
                # release the thread slot BEFORE firing the abort: a
                # supervised restart may re-arm immediately, and its
                # start() must spawn a fresh monitor instead of
                # no-opping on this dying one
                with self._monitor_lock:
                    self._thread = None
                if self.on_timeout is not None:
                    self.on_timeout(self)
                else:
                    # A SIGINT directed at the main thread (not just
                    # interrupt_main's flag) breaks a blocking sleep /
                    # syscall promptly; the train loops translate it
                    # back via the consume_abort() token. A step
                    # wedged inside non-interruptible C code needs
                    # on_timeout=lambda wd: os._exit(...) instead.
                    self._abort_error = self.hang_error()
                    self._abort_sent_at = time.monotonic()
                    try:
                        import signal as _signal
                        _signal.pthread_kill(
                            threading.main_thread().ident,
                            _signal.SIGINT)
                    except Exception:
                        import _thread
                        _thread.interrupt_main()
                return
        finally:
            # release only OUR slot: a re-arm may already have spawned
            # a fresh monitor into self._thread, which an unconditional
            # clear would orphan (two pollers after the next arm)
            with self._monitor_lock:
                if self._thread is threading.current_thread():
                    self._thread = None

    def _dump_step(self):
        print("=" * 60)
        print(f"[step watchdog] train step {self._armed_step} exceeded "
              f"{self.timeout_s}s")
        self._print_peer_report(
            empty_msg=("every rank's progress stalled at the same "
                       "step — suspect a wedged collective, not a "
                       "single peer") if self.collective_suspect
            else None)
        print("=" * 60)


def sys_frames():
    import sys
    return list(sys._current_frames().items())


_GLOBAL: Optional[CollectiveWatchdog] = None


def start_watchdog(timeout_s=None, interval_s=None, on_timeout=None):
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = CollectiveWatchdog(timeout_s, interval_s, on_timeout)
        _GLOBAL.start()
    return _GLOBAL


def stop_watchdog():
    global _GLOBAL
    if _GLOBAL is not None:
        _GLOBAL.stop()
        _GLOBAL = None
