"""Collective watchdog (reference: phi/core/distributed/
comm_task_manager.cc + nccl_comm_task.cc — records start/end of
collectives, detects hangs, dumps per-rank state).

TPU-native: XLA collectives can't be individually instrumented from
Python, so the watchdog monitors *device progress*: a heartbeat thread
issues a tiny probe computation every interval; if the device fails to
complete it within FLAGS_collective_timeout_s (a hung ICI collective /
dead coordinator blocks the stream), the watchdog dumps state and invokes
the timeout callback.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, Optional

import jax

from paddle_tpu.core.flags import get_flag


class CollectiveWatchdog:
    """Device-progress watchdog with cross-rank attribution.

    When `store` (or FLAGS_watchdog_store_root) is set, every rank
    publishes its progress — wall time of the last successful probe and
    an op counter from the dispatch layer — under
    ``watchdog/{job}/{rank}``. On a local timeout the dump reads every
    rank's published progress and names the straggler(s): ranks whose
    last heartbeat is older than the timeout (or missing entirely) —
    the role of the reference's comm_task_manager per-collective
    start/end records (comm_task_manager.cc), re-based on progress
    heartbeats because XLA collectives cannot be individually
    instrumented from Python."""

    def __init__(self, timeout_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 on_timeout: Optional[Callable] = None,
                 store=None, job_id: str = "default",
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None):
        self.timeout_s = timeout_s if timeout_s is not None else \
            get_flag("FLAGS_collective_timeout_s")
        self.interval_s = interval_s if interval_s is not None else \
            get_flag("FLAGS_watchdog_interval_s")
        self.on_timeout = on_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_ok = time.monotonic()
        self.tripped = False
        self.stragglers: Optional[list] = None
        self.job_id = job_id
        # rank/world resolved lazily: touching jax.process_index here
        # would force backend init for the common store-less watchdog
        self._rank = rank
        self._world_size = world_size
        if store is None:
            root = get_flag("FLAGS_watchdog_store_root")
            if root:
                from .elastic import FileKVStore
                store = FileKVStore(root)
        self.store = store
        self._op_count = 0
        self._unobserve = None
        if self.store is not None:
            from paddle_tpu.core import dispatch as _dispatch

            def _count(name, outs):
                self._op_count += 1
            self._unobserve = _dispatch.add_op_observer(_count)

    @property
    def rank(self):
        if self._rank is None:
            try:
                self._rank = jax.process_index()
            except Exception:
                self._rank = 0
        return self._rank

    @property
    def world_size(self):
        if self._world_size is None:
            try:
                self._world_size = jax.process_count()
            except Exception:
                pass
        return self._world_size

    def _publish(self):
        if self.store is None:
            return
        import json
        self.store.put(
            f"watchdog/{self.job_id}/{self.rank}",
            json.dumps({"ts": time.time(), "ops": self._op_count}))

    def _read_peers(self):
        if self.store is None:
            return {}
        import json
        out = {}
        for k, v in self.store.get_prefix(
                f"watchdog/{self.job_id}/").items():
            try:
                out[int(k.rsplit("/", 1)[-1])] = json.loads(v)
            except (ValueError, TypeError):
                pass
        return out

    def find_stragglers(self):
        """Ranks whose last published heartbeat is older than the
        timeout relative to the freshest rank, PLUS ranks that never
        published at all (expected via world_size — a peer that died
        before its first heartbeat must still be named)."""
        peers = self._read_peers()
        if not peers:
            return None
        newest = max(p["ts"] for p in peers.values())
        stale = [r for r, p in peers.items()
                 if newest - p["ts"] > min(self.timeout_s,
                                           2 * self.interval_s + 1.0)]
        missing = []
        if self.world_size:
            missing = [r for r in range(self.world_size)
                       if r not in peers]
        return sorted(set(stale) | set(missing))

    def _probe_once(self) -> bool:
        done = threading.Event()

        def work():
            try:
                import jax.numpy as jnp
                (jnp.zeros(()) + 1).block_until_ready()
                done.set()
            except Exception:
                pass

        t = threading.Thread(target=work, daemon=True)
        t.start()
        return done.wait(self.timeout_s)

    def _loop(self):
        try:
            self._publish()
            while not self._stop.wait(self.interval_s):
                if self._probe_once():
                    self.last_ok = time.monotonic()
                    self._publish()
                else:
                    self.tripped = True
                    self._dump()
                    if self.on_timeout is not None:
                        self.on_timeout(self)
                    return
        finally:
            # allow a later start() to re-arm monitoring after a trip
            self._thread = None

    def _dump(self):
        print("=" * 60)
        print("[collective watchdog] device probe timed out after "
              f"{self.timeout_s}s — possible hung collective / dead "
              "coordination service")
        try:
            print("process_index:", jax.process_index(),
                  "device_count:", len(jax.devices()))
        except Exception:
            pass
        self.stragglers = self.find_stragglers()
        if self.stragglers is not None:
            peers = self._read_peers()
            print("per-rank progress (published heartbeats):")
            now = time.time()
            for r in sorted(peers):
                p = peers[r]
                tag = "  <-- STRAGGLER" if r in self.stragglers else ""
                print(f"  rank {r}: ops={p.get('ops')} "
                      f"last_heartbeat={now - p['ts']:.1f}s ago{tag}")
            if self.stragglers:
                print(f"suspected straggler rank(s): {self.stragglers}")
            else:
                print("all ranks show fresh heartbeats — suspect the "
                      "local device/runtime, not a peer")
        dump_path = get_flag("FLAGS_memory_stats_dump_path")
        if dump_path:
            try:
                from paddle_tpu import device as _device
                _device.dump_memory_stats(dump_path)
                print(f"memory stats dumped to {dump_path}")
            except Exception:
                pass
        print("live python threads:")
        for tid, frame in sys_frames():
            print(f"  thread {tid}:")
            print("   " + "   ".join(traceback.format_stack(frame)[-3:]))
        print("=" * 60)

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
        if self._unobserve is not None:
            self._unobserve()
            self._unobserve = None


def sys_frames():
    import sys
    return list(sys._current_frames().items())


_GLOBAL: Optional[CollectiveWatchdog] = None


def start_watchdog(timeout_s=None, interval_s=10.0, on_timeout=None):
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = CollectiveWatchdog(timeout_s, interval_s, on_timeout)
        _GLOBAL.start()
    return _GLOBAL


def stop_watchdog():
    global _GLOBAL
    if _GLOBAL is not None:
        _GLOBAL.stop()
        _GLOBAL = None
