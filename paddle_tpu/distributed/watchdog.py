"""Collective watchdog (reference: phi/core/distributed/
comm_task_manager.cc + nccl_comm_task.cc — records start/end of
collectives, detects hangs, dumps per-rank state).

TPU-native: XLA collectives can't be individually instrumented from
Python, so the watchdog monitors *device progress*: a heartbeat thread
issues a tiny probe computation every interval; if the device fails to
complete it within FLAGS_collective_timeout_s (a hung ICI collective /
dead coordinator blocks the stream), the watchdog dumps state and invokes
the timeout callback.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, Optional

import jax

from paddle_tpu.core.flags import get_flag


class CollectiveWatchdog:
    def __init__(self, timeout_s: Optional[float] = None,
                 interval_s: float = 10.0,
                 on_timeout: Optional[Callable] = None):
        self.timeout_s = timeout_s if timeout_s is not None else \
            get_flag("FLAGS_collective_timeout_s")
        self.interval_s = interval_s
        self.on_timeout = on_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_ok = time.monotonic()
        self.tripped = False

    def _probe_once(self) -> bool:
        done = threading.Event()

        def work():
            try:
                import jax.numpy as jnp
                (jnp.zeros(()) + 1).block_until_ready()
                done.set()
            except Exception:
                pass

        t = threading.Thread(target=work, daemon=True)
        t.start()
        return done.wait(self.timeout_s)

    def _loop(self):
        try:
            while not self._stop.wait(self.interval_s):
                if self._probe_once():
                    self.last_ok = time.monotonic()
                else:
                    self.tripped = True
                    self._dump()
                    if self.on_timeout is not None:
                        self.on_timeout(self)
                    return
        finally:
            # allow a later start() to re-arm monitoring after a trip
            self._thread = None

    def _dump(self):
        print("=" * 60)
        print("[collective watchdog] device probe timed out after "
              f"{self.timeout_s}s — possible hung collective / dead "
              "coordination service")
        try:
            print("process_index:", jax.process_index(),
                  "device_count:", len(jax.devices()))
        except Exception:
            pass
        print("live python threads:")
        for tid, frame in sys_frames():
            print(f"  thread {tid}:")
            print("   " + "   ".join(traceback.format_stack(frame)[-3:]))
        print("=" * 60)

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None


def sys_frames():
    import sys
    return list(sys._current_frames().items())


_GLOBAL: Optional[CollectiveWatchdog] = None


def start_watchdog(timeout_s=None, interval_s=10.0, on_timeout=None):
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = CollectiveWatchdog(timeout_s, interval_s, on_timeout)
        _GLOBAL.start()
    return _GLOBAL


def stop_watchdog():
    global _GLOBAL
    if _GLOBAL is not None:
        _GLOBAL.stop()
        _GLOBAL = None
