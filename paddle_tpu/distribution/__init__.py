"""paddle.distribution equivalent (reference: python/paddle/distribution/*).

Distributions wrap jax.scipy stats + jax.random sampling through the
paddle_tpu Tensor/op layer (rsample is differentiable via the
reparameterization trick where defined).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core import generator as gen_mod
from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else paddle.to_tensor(x)


def _shape(sample_shape, base_shape):
    return tuple(int(s) for s in sample_shape) + tuple(base_shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        with paddle.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return paddle.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(loc, scale):
            z = jax.random.normal(key, shp, loc.dtype)
            return loc + scale * z
        return run_op("normal_rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return run_op("normal_log_prob", f, _t(value), self.loc, self.scale)

    def entropy(self):
        return run_op("normal_entropy",
                      lambda s: 0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(s) + jnp.zeros(self.batch_shape, s.dtype),
                      self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.low.shape), tuple(self.high.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(lo, hi):
            u = jax.random.uniform(key, shp, lo.dtype)
            return lo + (hi - lo) * u
        return run_op("uniform_rsample", f, self.low, self.high)

    sample = Distribution.sample

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return run_op("uniform_log_prob", f, _t(value), self.low, self.high)

    def entropy(self):
        return paddle.log(self.high - self.low)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is None:
            self.logits = _t(logits)
            self.probs = paddle.sigmoid(self.logits)
        else:
            self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    def sample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(p):
            return jax.random.bernoulli(key, p, shp).astype(p.dtype)
        return run_op("bernoulli_sample", f, self.probs,
                      differentiable=False)

    def log_prob(self, value):
        def f(v, p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return run_op("bernoulli_log_prob", f, _t(value), self.probs)

    def entropy(self):
        def f(p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return run_op("bernoulli_entropy", f, self.probs)

    @property
    def mean(self):
        return self.probs


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = paddle.log(_t(probs))
        super().__init__(tuple(self.logits.shape[:-1]))

    @property
    def probs(self):
        from paddle_tpu.nn.functional import softmax
        return softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(lg):
            return jax.random.categorical(key, lg, shape=shp)
        return run_op("categorical_sample", f, self.logits,
                      differentiable=False)

    def log_prob(self, value):
        def f(v, lg):
            logp = jax.nn.log_softmax(lg, -1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], -1)[..., 0]
        return run_op("categorical_log_prob", f, _t(value), self.logits)

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, -1)
            return -jnp.sum(jnp.exp(logp) * logp, -1)
        return run_op("categorical_entropy", f, self.logits)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(r):
            return jax.random.exponential(key, shp, r.dtype) / r
        return run_op("exponential_rsample", f, self.rate)

    def log_prob(self, value):
        return run_op("exponential_log_prob",
                      lambda v, r: jnp.log(r) - r * v, _t(value), self.rate)

    def entropy(self):
        return 1.0 - paddle.log(self.rate)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.concentration.shape), tuple(self.rate.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(a, r):
            return jax.random.gamma(key, jnp.broadcast_to(a, shp)) / r
        return run_op("gamma_rsample", f, self.concentration, self.rate)

    def log_prob(self, value):
        def f(v, a, r):
            return a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v \
                - jax.lax.lgamma(a)
        return run_op("gamma_log_prob", f, _t(value), self.concentration,
                      self.rate)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.alpha.shape), tuple(self.beta.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(a, b):
            return jax.random.beta(key, jnp.broadcast_to(a, shp),
                                   jnp.broadcast_to(b, shp))
        return run_op("beta_rsample", f, self.alpha, self.beta)

    def log_prob(self, value):
        def f(v, a, b):
            betaln = jax.lax.lgamma(a) + jax.lax.lgamma(b) \
                - jax.lax.lgamma(a + b)
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - betaln
        return run_op("beta_log_prob", f, _t(value), self.alpha, self.beta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        super().__init__(tuple(self.concentration.shape[:-1]),
                         tuple(self.concentration.shape[-1:]))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.concentration.shape)
        def f(a):
            g = jax.random.gamma(key, jnp.broadcast_to(a, shp))
            return g / jnp.sum(g, -1, keepdims=True)
        return run_op("dirichlet_rsample", f, self.concentration)

    def log_prob(self, value):
        def f(v, a):
            lnB = jnp.sum(jax.lax.lgamma(a), -1) \
                - jax.lax.lgamma(jnp.sum(a, -1))
            return jnp.sum((a - 1) * jnp.log(v), -1) - lnB
        return run_op("dirichlet_log_prob", f, _t(value),
                      self.concentration)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(loc, s):
            return loc + s * jax.random.laplace(key, shp, loc.dtype)
        return run_op("laplace_rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        return run_op("laplace_log_prob",
                      lambda v, loc, s: -jnp.abs(v - loc) / s
                      - jnp.log(2 * s), _t(value), self.loc, self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(loc, s):
            return loc + s * jax.random.gumbel(key, shp, loc.dtype)
        return run_op("gumbel_rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, loc, s):
            z = (v - loc) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return run_op("gumbel_log_prob", f, _t(value), self.loc, self.scale)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._normal = Normal(loc, scale)
        self.loc = self._normal.loc
        self.scale = self._normal.scale
        super().__init__(self._normal.batch_shape)

    def rsample(self, shape=()):
        return paddle.exp(self._normal.rsample(shape))

    def log_prob(self, value):
        v = _t(value)
        return self._normal.log_prob(paddle.log(v)) - paddle.log(v)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape[:-1]),
                         tuple(self.probs.shape[-1:]))

    def sample(self, shape=()):
        key = gen_mod.next_key()
        def f(p):
            logits = jnp.log(jnp.maximum(p, 1e-30))
            draws = jax.random.categorical(
                key, logits, shape=tuple(shape) + (self.total_count,)
                + tuple(self.probs.shape[:-1]))
            k = p.shape[-1]
            oh = jax.nn.one_hot(draws, k, dtype=p.dtype)
            axis = len(tuple(shape))
            return jnp.sum(oh, axis=axis)
        return run_op("multinomial_sample", f, self.probs,
                      differentiable=False)

    def log_prob(self, value):
        def f(v, p):
            logp = jnp.log(jnp.maximum(p, 1e-30))
            return jax.lax.lgamma(jnp.asarray(self.total_count + 1.0)) \
                - jnp.sum(jax.lax.lgamma(v + 1.0), -1) \
                + jnp.sum(v * logp, -1)
        return run_op("multinomial_log_prob", f, _t(value), self.probs)


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    def sample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        def f(p):
            return jax.random.geometric(key, p, shp).astype(p.dtype)
        return run_op("geometric_sample", f, self.probs,
                      differentiable=False)

    def log_prob(self, value):
        return run_op("geometric_log_prob",
                      lambda v, p: (v - 1) * jnp.log1p(-p) + jnp.log(p),
                      _t(value), self.probs)


# ---------------------------- KL registry ----------------------------------
_KL = {}


def register_kl(cls_p, cls_q):
    def deco(fn):
        _KL[(cls_p, cls_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def f(lp, sp, lq, sq):
        var_ratio = (sp / sq) ** 2
        t1 = ((lp - lq) / sq) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return run_op("kl_normal", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Categorical, Categorical)
def _kl_cat(p, q):
    def f(lp, lq):
        a = jax.nn.log_softmax(lp, -1)
        b = jax.nn.log_softmax(lq, -1)
        return jnp.sum(jnp.exp(a) * (a - b), -1)
    return run_op("kl_categorical", f, p.logits, q.logits)


@register_kl(Bernoulli, Bernoulli)
def _kl_bern(p, q):
    def f(pp, pq):
        eps = 1e-7
        pp = jnp.clip(pp, eps, 1 - eps)
        pq = jnp.clip(pq, eps, 1 - eps)
        return pp * (jnp.log(pp) - jnp.log(pq)) + \
            (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-pq))
    return run_op("kl_bernoulli", f, p.probs, q.probs)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def f(pl, ph, ql, qh):
        res = jnp.log((qh - ql) / (ph - pl))
        return jnp.where((ql <= pl) & (ph <= qh), res, jnp.inf)
    return run_op("kl_uniform", f, p.low, p.high, q.low, q.high)


# --------------------------------------------------------------------------
# Extended families + transform library (separate modules, reference file
# layout: python/paddle/distribution/{poisson,binomial,...,transform}.py)
from .families import (  # noqa: E402,F401
    Binomial, Cauchy, Chi2, ContinuousBernoulli, ExponentialFamily,
    Independent, LKJCholesky, MultivariateNormal, Poisson, StudentT,
    TransformedDistribution,
)
from . import transform  # noqa: E402,F401
from .transform import (  # noqa: E402,F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform, Transform,
)
