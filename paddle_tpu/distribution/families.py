"""Additional distribution families completing the reference inventory
(python/paddle/distribution/: poisson, binomial, cauchy, chi2,
student_t, multivariate_normal, continuous_bernoulli,
exponential_family, independent, transformed_distribution,
lkj_cholesky).

Same idiom as __init__: jax.random sampling keyed off the global
generator, log_prob/entropy as traced ops through run_op.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core import generator as gen_mod
from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else paddle.to_tensor(x)


def _shape(sample_shape, base_shape):
    return tuple(int(s) for s in sample_shape) + tuple(base_shape)


from . import Distribution, Gamma, register_kl  # noqa: E402


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference
    exponential_family.py): entropy via the Bregman divergence of the
    log-normalizer — implemented with jax.grad over the natural
    parameters, replacing the reference's C++ double-backward."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        nat = [p._data if isinstance(p, Tensor) else jnp.asarray(p)
               for p in self._natural_parameters]

        def f(*nat):
            lg = self._log_normalizer(*nat)
            grads = jax.grad(lambda *n: jnp.sum(self._log_normalizer(*n)),
                             argnums=tuple(range(len(nat))))(*nat)
            ent = lg - self._mean_carrier_measure
            for n, g in zip(nat, grads):
                ent = ent - n * g
            return ent
        return run_op("expfam_entropy", f, *[Tensor._wrap(n, True)
                                             for n in nat])


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        return run_op(
            "poisson_sample",
            lambda r: jax.random.poisson(key, r, shp).astype(r.dtype),
            self.rate)

    def log_prob(self, value):
        return run_op(
            "poisson_log_prob",
            lambda v, r: v * jnp.log(r) - r - jax.lax.lgamma(v + 1.0),
            _t(value), self.rate)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    @property
    def _natural_parameters(self):
        return [paddle.log(self.rate)]

    def _log_normalizer(self, eta):
        return jnp.exp(eta)

    # The Bregman identity needs E[log k!] (the carrier mean), which has
    # no closed form for Poisson — sum the series directly for small
    # rates; the k<192 grid covers rate<96 (mass within 10 sigma), and
    # the Edgeworth asymptotic takes over beyond it.
    def entropy(self):
        def f(r):
            ks = jnp.arange(0.0, 192.0, dtype=r.dtype)
            shape = (ks.shape[0],) + (1,) * r.ndim
            ks = ks.reshape(shape)
            rs = jnp.minimum(r, 96.0)
            logp = ks * jnp.log(rs) - rs - jax.lax.lgamma(ks + 1.0)
            p = jnp.exp(logp)
            series = -jnp.sum(jnp.where(p > 0, p * logp, 0.0), 0)
            asym = 0.5 * jnp.log(2 * math.pi * math.e * r) \
                - 1.0 / (12 * r) - 1.0 / (24 * r * r) \
                - 19.0 / (360 * r ** 3)
            return jnp.where(r < 96.0, series, asym)
        return run_op("poisson_entropy", f, self.rate)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.total_count.shape), tuple(self.probs.shape))))

    def sample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        return run_op(
            "binomial_sample",
            lambda n, p: jax.random.binomial(key, n, p, shape=shp)
            .astype(p.dtype), self.total_count, self.probs)

    def log_prob(self, value):
        def f(v, n, p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            logc = jax.lax.lgamma(n + 1.0) - jax.lax.lgamma(v + 1.0) \
                - jax.lax.lgamma(n - v + 1.0)
            return logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return run_op("binomial_log_prob", f, _t(value),
                      self.total_count, self.probs)

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)
        return run_op(
            "cauchy_rsample",
            lambda l, s: l + s * jax.random.cauchy(key, shp, l.dtype),
            self.loc, self.scale)

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -jnp.log(math.pi) - jnp.log(s) - jnp.log1p(z * z)
        return run_op("cauchy_log_prob", f, _t(value), self.loc, self.scale)

    def entropy(self):
        return run_op(
            "cauchy_entropy",
            lambda s: jnp.log(4 * math.pi) + jnp.log(s), self.scale)

    def cdf(self, value):
        def f(v, l, s):
            return jnp.arctan((v - l) / s) / math.pi + 0.5
        return run_op("cauchy_cdf", f, _t(value), self.loc, self.scale)


class Chi2(Gamma):
    """Chi-squared(df) == Gamma(df/2, 1/2) (reference chi2.py)."""

    def __init__(self, df, name=None):
        self.df = _t(df)
        super().__init__(self.df * 0.5, paddle.full_like(self.df, 0.5))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.df.shape), tuple(self.loc.shape),
            tuple(self.scale.shape))))

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)

        def f(df, l, s):
            return l + s * jax.random.t(key, df, shp, l.dtype)
        return run_op("studentt_rsample", f, self.df, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, df, l, s):
            z = (v - l) / s
            # log B(1/2, df/2); lgamma(1/2) = 0.5 log(pi)
            lbeta = jax.lax.lgamma(0.5 * df) + 0.5 * math.log(math.pi) \
                - jax.lax.lgamma(0.5 * (df + 1.0))
            return -0.5 * (df + 1.0) * jnp.log1p(z * z / df) \
                - 0.5 * jnp.log(df) - lbeta - jnp.log(s)
        return run_op("studentt_log_prob", f, _t(value), self.df,
                      self.loc, self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        def f(df, s):
            return jnp.where(df > 2.0, s * s * df / (df - 2.0), jnp.inf)
        return run_op("studentt_var", f, self.df, self.scale)


class MultivariateNormal(Distribution):
    """N(loc, Σ) with Σ given as covariance_matrix or scale_tril
    (reference multivariate_normal.py). Sampling and log_prob go
    through the Cholesky factor — triangular ops the MXU handles well."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _t(loc)
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
        elif covariance_matrix is not None:
            self.scale_tril = run_op(
                "mvn_chol", lambda c: jnp.linalg.cholesky(c),
                _t(covariance_matrix))
        elif precision_matrix is not None:
            def f(p):
                lp = jnp.linalg.cholesky(p)
                eye = jnp.eye(p.shape[-1], dtype=p.dtype)
                inv = jax.scipy.linalg.solve_triangular(lp, eye, lower=True)
                return jnp.linalg.cholesky(inv.T @ inv)
            self.scale_tril = run_op("mvn_prec_chol", f,
                                     _t(precision_matrix))
        else:
            raise ValueError("need covariance_matrix, precision_matrix or "
                             "scale_tril")
        d = self.loc.shape[-1]
        super().__init__(tuple(self.loc.shape[:-1]), (d,))

    @property
    def covariance_matrix(self):
        return run_op("mvn_cov",
                      lambda lt: lt @ jnp.swapaxes(lt, -1, -2),
                      self.scale_tril)

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape) + self.event_shape

        def f(loc, lt):
            z = jax.random.normal(key, shp, loc.dtype)
            return loc + jnp.einsum("...ij,...j->...i", lt, z)
        return run_op("mvn_rsample", f, self.loc, self.scale_tril)

    def log_prob(self, value):
        def f(v, loc, lt):
            d = loc.shape[-1]
            dev = v - loc
            m = jax.scipy.linalg.solve_triangular(
                lt, dev[..., None], lower=True)[..., 0]
            half_logdet = jnp.sum(jnp.log(jnp.diagonal(
                lt, axis1=-2, axis2=-1)), -1)
            return -0.5 * jnp.sum(m * m, -1) - half_logdet \
                - 0.5 * d * math.log(2 * math.pi)
        return run_op("mvn_log_prob", f, _t(value), self.loc,
                      self.scale_tril)

    def entropy(self):
        def f(lt):
            d = lt.shape[-1]
            half_logdet = jnp.sum(jnp.log(jnp.diagonal(
                lt, axis1=-2, axis2=-1)), -1)
            return 0.5 * d * (1.0 + math.log(2 * math.pi)) + half_logdet
        return run_op("mvn_entropy", f, self.scale_tril)

    @property
    def mean(self):
        return self.loc


class ContinuousBernoulli(Distribution):
    """CB(λ) (reference continuous_bernoulli.py): density
    C(λ) λ^x (1-λ)^(1-x) on [0,1]."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(tuple(self.probs.shape))

    def _log_const(self, p):
        # log C(λ); Taylor expansion near 0.5 for stability
        near = jnp.abs(p - 0.5) < (self._lims[1] - self._lims[0]) / 2
        psafe = jnp.where(near, 0.4, p)
        logc = jnp.log(
            (2 * jnp.arctanh(1 - 2 * psafe)) / (1 - 2 * psafe))
        taylor = math.log(2.0) + 4.0 / 3.0 * (p - 0.5) ** 2
        return jnp.where(near, taylor, logc)

    def log_prob(self, value):
        def f(v, p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return self._log_const(p) + v * jnp.log(p) \
                + (1 - v) * jnp.log1p(-p)
        return run_op("cb_log_prob", f, _t(value), self.probs)

    def rsample(self, shape=()):
        key = gen_mod.next_key()
        shp = _shape(shape, self.batch_shape)

        def f(p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            u = jax.random.uniform(key, shp, p.dtype, minval=eps,
                                   maxval=1 - eps)
            near = jnp.abs(p - 0.5) < (self._lims[1] - self._lims[0]) / 2
            psafe = jnp.where(near, 0.4, p)
            # inverse CDF for λ != 0.5
            icdf = (jnp.log1p(u * (2 * psafe - 1) / (1 - psafe))
                    ) / (jnp.log(psafe) - jnp.log1p(-psafe))
            return jnp.where(near, u, icdf)
        return run_op("cb_rsample", f, self.probs)

    @property
    def mean(self):
        def f(p):
            near = jnp.abs(p - 0.5) < (self._lims[1] - self._lims[0]) / 2
            psafe = jnp.where(near, 0.4, p)
            m = psafe / (2 * psafe - 1) + 1.0 / (
                2 * jnp.arctanh(1 - 2 * psafe))
            return jnp.where(near, 0.5, m)
        return run_op("cb_mean", f, self.probs)


class Independent(Distribution):
    """Reinterpret trailing batch dims as event dims (reference
    independent.py): log_prob sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        if self.rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        split = len(base.batch_shape) - self.rank
        super().__init__(base.batch_shape[:split],
                         base.batch_shape[split:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return run_op(
            "independent_sum",
            lambda l: jnp.sum(l, axis=tuple(range(-self.rank, 0))), lp)

    def entropy(self):
        ent = self.base.entropy()
        return run_op(
            "independent_ent_sum",
            lambda e: jnp.sum(e, axis=tuple(range(-self.rank, 0))), ent)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance


class TransformedDistribution(Distribution):
    """Push a base distribution through transforms (reference
    transformed_distribution.py). Event-rank bookkeeping follows the
    reference/torch algorithm: walking the transforms in reverse, each
    log-det term and the base log_prob are summed down to batch shape."""

    def __init__(self, base, transforms):
        from .transform import ChainTransform, Transform
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        base_event_rank = len(base.event_shape)
        self._out_event_rank = max(
            self._chain._codomain_event_rank,
            base_event_rank - self._chain._domain_event_rank
            + self._chain._codomain_event_rank)
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out = tuple(self._chain.forward_shape(shape))
        split = len(out) - self._out_event_rank
        super().__init__(out[:split], out[split:])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self._chain.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self._chain.forward(x)

    def log_prob(self, value):
        from .transform import sum_rightmost
        y = _t(value)
        event_rank = self._out_event_rank
        lp = None
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ldj = sum_rightmost(
                t.forward_log_det_jacobian(x),
                event_rank - t._codomain_event_rank)
            lp = (-ldj) if lp is None else lp - ldj
            event_rank += t._domain_event_rank - t._codomain_event_rank
            y = x
        base_lp = sum_rightmost(self.base.log_prob(y),
                                event_rank - len(self.base.event_shape))
        return base_lp if lp is None else lp + base_lp


class LKJCholesky(Distribution):
    """LKJ prior over correlation-matrix Cholesky factors (reference
    lkj_cholesky.py), sampled with the onion method."""

    def __init__(self, dim, concentration=1.0, sample_method="onion",
                 name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self.dim = int(dim)
        self.concentration = _t(concentration)
        self.sample_method = sample_method
        super().__init__(tuple(self.concentration.shape),
                         (self.dim, self.dim))

    def sample(self, shape=()):
        key = gen_mod.next_key()
        d = self.dim
        shp = _shape(shape, self.batch_shape)

        def f(conc):
            ks = jax.random.split(key, 2 * d)
            # onion: row i built from a Beta-distributed radius and a
            # uniform direction on the sphere
            L = jnp.zeros(shp + (d, d), conc.dtype)
            L = L.at[..., 0, 0].set(1.0)
            for i in range(1, d):
                alpha = conc + 0.5 * (d - 1 - i)
                beta_s = jax.random.beta(
                    ks[2 * i], i / 2.0, alpha, shp).astype(conc.dtype)
                u = jax.random.normal(ks[2 * i + 1], shp + (i,),
                                      conc.dtype)
                u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
                w = jnp.sqrt(beta_s)[..., None] * u
                L = L.at[..., i, :i].set(w)
                L = L.at[..., i, i].set(
                    jnp.sqrt(jnp.clip(1.0 - beta_s, 1e-12)))
            return L
        return run_op("lkj_sample", f, self.concentration)

    def log_prob(self, value):
        def f(L, conc):
            d = self.dim
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            order = jnp.arange(2, d + 1, dtype=L.dtype)
            exponents = 2 * (conc[..., None] - 1.0) + d - order
            lp = jnp.sum(exponents * jnp.log(diag), -1)
            # normalizer (Stan reference form)
            dm1 = d - 1
            ks = jnp.arange(1, d, dtype=L.dtype)
            alpha = conc[..., None] + 0.5 * (d - ks - 1.0)
            logpi = 0.5 * ks * math.log(math.pi)
            lnorm = jnp.sum(
                logpi + jax.lax.lgamma(alpha)
                - jax.lax.lgamma(alpha + 0.5 * ks), -1)
            return lp - lnorm
        return run_op("lkj_log_prob", f, _t(value), self.concentration)


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return run_op(
        "kl_poisson",
        lambda rp, rq: rp * (jnp.log(rp) - jnp.log(rq)) - rp + rq,
        p.rate, q.rate)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    def f(lp, sp, lq, sq):
        return jnp.log(((sp + sq) ** 2 + (lp - lq) ** 2)
                       / (4 * sp * sq))
    return run_op("kl_cauchy", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    def f(lp, ltp, lq, ltq):
        d = lp.shape[-1]
        m = jax.scipy.linalg.solve_triangular(ltq, ltp, lower=True)
        tr = jnp.sum(m * m, (-2, -1))
        dev = jax.scipy.linalg.solve_triangular(
            ltq, (lq - lp)[..., None], lower=True)[..., 0]
        maha = jnp.sum(dev * dev, -1)
        logdet = 2 * (jnp.sum(jnp.log(jnp.diagonal(
            ltq, axis1=-2, axis2=-1)), -1)
            - jnp.sum(jnp.log(jnp.diagonal(ltp, axis1=-2, axis2=-1)), -1))
        return 0.5 * (tr + maha - d + logdet)
    return run_op("kl_mvn", f, p.loc, p.scale_tril, q.loc, q.scale_tril)
