"""Bijective transforms (reference: python/paddle/distribution/transform.py
— Transform, Abs/Affine/Chain/Exp/Independent/Power/Reshape/Sigmoid/
Softmax/Stack/StickBreaking/Tanh transforms).

Each transform exposes forward / inverse / forward_log_det_jacobian over
paddle_tpu Tensors; TransformedDistribution composes them with a base
distribution. All math routes through the op layer so it traces into XLA
like any other op.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor


class _Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"


def _t(x):
    return x if isinstance(x, Tensor) else paddle.to_tensor(x)


def sum_rightmost(x, n):
    """Sum a Tensor over its last `n` dims (n == 0 -> unchanged)."""
    if n <= 0:
        return x
    return run_op(
        "sum_rightmost",
        lambda a: jnp.sum(a, axis=tuple(range(-n, 0))), _t(x))


class Transform:
    _type = _Type.BIJECTION

    def forward(self, x):
        return self._forward(_t(x))

    def inverse(self, y):
        return self._inverse(_t(y))

    def forward_log_det_jacobian(self, x):
        return self._forward_log_det_jacobian(_t(x))

    def inverse_log_det_jacobian(self, y):
        return -self._forward_log_det_jacobian(self.inverse(y))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # event dims consumed/produced (reference _domain/_codomain event_rank)
    _domain_event_rank = 0
    _codomain_event_rank = 0


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return run_op("affine_fldj",
                      lambda s, x: jnp.broadcast_to(
                          jnp.log(jnp.abs(s)), x.shape),
                      self.scale, x)


class ExpTransform(Transform):
    def _forward(self, x):
        return paddle.exp(x)

    def _inverse(self, y):
        return paddle.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def _forward(self, x):
        return x ** self.power

    def _inverse(self, y):
        return y ** (1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return run_op(
            "power_fldj",
            lambda p, x: jnp.log(jnp.abs(p * x ** (p - 1))), self.power, x)


class SigmoidTransform(Transform):
    def _forward(self, x):
        return paddle.sigmoid(x)

    def _inverse(self, y):
        return run_op("sigmoid_inv",
                      lambda y: jnp.log(y) - jnp.log1p(-y), y)

    def _forward_log_det_jacobian(self, x):
        return run_op(
            "sigmoid_fldj",
            lambda x: -jax.nn.softplus(-x) - jax.nn.softplus(x), x)


class TanhTransform(Transform):
    def _forward(self, x):
        return paddle.tanh(x)

    def _inverse(self, y):
        return run_op("tanh_inv", lambda y: jnp.arctanh(y), y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log 2 - x - softplus(-2x))
        return run_op(
            "tanh_fldj",
            lambda x: 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x)),
            x)


class AbsTransform(Transform):
    """y = |x| — a surjection; inverse returns the positive branch."""
    _type = _Type.SURJECTION

    def _forward(self, x):
        return paddle.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("AbsTransform is not injective")


class SoftmaxTransform(Transform):
    """x -> softmax(x) over the last dim (surjection onto the simplex)."""
    _type = _Type.OTHER
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        return run_op("softmax_fwd", lambda x: jax.nn.softmax(x, -1), x)

    def _inverse(self, y):
        return paddle.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("softmax is not a bijection")


class StickBreakingTransform(Transform):
    """R^{K-1} -> K-simplex via stick breaking (bijection)."""
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        def f(x):
            k = x.shape[-1]
            offset = jnp.arange(k, 0, -1, dtype=x.dtype)
            z = jax.nn.sigmoid(x - jnp.log(offset))
            zpad = jnp.concatenate(
                [z, jnp.ones(x.shape[:-1] + (1,), x.dtype)], -1)
            cum = jnp.concatenate(
                [jnp.ones(x.shape[:-1] + (1,), x.dtype),
                 jnp.cumprod(1 - z, -1)], -1)
            return zpad * cum
        return run_op("stickbreak_fwd", f, x)

    def _inverse(self, y):
        def f(y):
            cum = jnp.cumsum(y[..., :-1], -1)
            rem = 1.0 - jnp.concatenate(
                [jnp.zeros(y.shape[:-1] + (1,), y.dtype),
                 cum[..., :-1]], -1)
            z = y[..., :-1] / rem
            k = y.shape[-1] - 1
            offset = jnp.arange(k, 0, -1, dtype=y.dtype)
            return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)
        return run_op("stickbreak_inv", f, y)

    def _forward_log_det_jacobian(self, x):
        # dy_k/dz_k = remaining stick before k; dz_k/dt_k = sig(t)sig(-t)
        def f(x):
            k = x.shape[-1]
            offset = jnp.arange(k, 0, -1, dtype=x.dtype)
            t = x - jnp.log(offset)
            z = jax.nn.sigmoid(t)
            remaining = jnp.concatenate(
                [jnp.ones(x.shape[:-1] + (1,), x.dtype),
                 jnp.cumprod(1 - z, -1)[..., :-1]], -1)
            return jnp.sum(jax.nn.log_sigmoid(t) + jax.nn.log_sigmoid(-t)
                           + jnp.log(remaining), -1)
        return run_op("stickbreak_fldj", f, x)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    """Composition t_n(...t_1(x))."""

    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._domain_event_rank = max(
            (t._domain_event_rank for t in self.transforms), default=0)
        self._codomain_event_rank = self._domain_event_rank

    def _forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        # Mixed event ranks: each term is summed down to the chain's
        # event rank before accumulation (reference ChainTransform /
        # torch ComposeTransform semantics) so an elementwise ldj and an
        # event-rank-1 ldj add at the same (batch) shape.
        event_rank = self._domain_event_rank
        total = None
        for t in self.transforms:
            ldj = sum_rightmost(t.forward_log_det_jacobian(x),
                                event_rank - t._domain_event_rank)
            total = ldj if total is None else total + ldj
            x = t.forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape


class IndependentTransform(Transform):
    """Reinterpret `reinterpreted_batch_rank` batch dims as event dims:
    the log-det sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._domain_event_rank = base._domain_event_rank + self.rank
        self._codomain_event_rank = base._codomain_event_rank + self.rank

    def _forward(self, x):
        return self.base.forward(x)

    def _inverse(self, y):
        return self.base.inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base.forward_log_det_jacobian(x)
        return run_op(
            "indep_fldj",
            lambda l: jnp.sum(l, axis=tuple(range(-self.rank, 0))), ldj)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(int(s) for s in in_event_shape)
        self.out_event_shape = tuple(int(s) for s in out_event_shape)
        import numpy as _np
        if _np.prod(self.in_event_shape, dtype=int) != \
                _np.prod(self.out_event_shape, dtype=int):
            raise ValueError("in/out event sizes must match")
        self._domain_event_rank = len(self.in_event_shape)
        self._codomain_event_rank = len(self.out_event_shape)

    def _forward(self, x):
        batch = tuple(x.shape)[:x.ndim - len(self.in_event_shape)]
        return paddle.reshape(x, batch + self.out_event_shape)

    def _inverse(self, y):
        batch = tuple(y.shape)[:y.ndim - len(self.out_event_shape)]
        return paddle.reshape(y, batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = tuple(x.shape)[:x.ndim - len(self.in_event_shape)]
        return paddle.zeros(batch if batch else (1,))

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:-n] if n else shape) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:-n] if n else shape) + self.in_event_shape


class StackTransform(Transform):
    """Apply transforms[i] to slice i along `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, x, method):
        parts = []
        for i, t in enumerate(self.transforms):
            sl = paddle.slice(x, [self.axis], [i], [i + 1])
            parts.append(getattr(t, method)(sl))
        return paddle.concat(parts, axis=self.axis)

    def _forward(self, x):
        return self._map(x, "forward")

    def _inverse(self, y):
        return self._map(y, "inverse")

    def _forward_log_det_jacobian(self, x):
        return self._map(x, "forward_log_det_jacobian")
