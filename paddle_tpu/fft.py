"""paddle.fft equivalent over jnp.fft (reference: python/paddle/fft.py over
pocketfft/cuFFT kernels — XLA lowers FFT natively)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import run_op


def _fft_op(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return run_op(name, lambda a: jfn(a, n=n, axis=axis, norm=norm), x)
    op.__name__ = name
    return op


def _fftn_op(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name_arg=None):
        return run_op(name, lambda a: jfn(a, s=s, axes=axes, norm=norm), x)
    op.__name__ = name
    return op


fft = _fft_op("fft", jnp.fft.fft)
ifft = _fft_op("ifft", jnp.fft.ifft)
rfft = _fft_op("rfft", jnp.fft.rfft)
irfft = _fft_op("irfft", jnp.fft.irfft)
hfft = _fft_op("hfft", jnp.fft.hfft)
ihfft = _fft_op("ihfft", jnp.fft.ihfft)
fft2 = _fftn_op("fft2", lambda a, s, axes, norm: jnp.fft.fft2(
    a, s=s, axes=axes or (-2, -1), norm=norm))
ifft2 = _fftn_op("ifft2", lambda a, s, axes, norm: jnp.fft.ifft2(
    a, s=s, axes=axes or (-2, -1), norm=norm))
rfft2 = _fftn_op("rfft2", lambda a, s, axes, norm: jnp.fft.rfft2(
    a, s=s, axes=axes or (-2, -1), norm=norm))
irfft2 = _fftn_op("irfft2", lambda a, s, axes, norm: jnp.fft.irfft2(
    a, s=s, axes=axes or (-2, -1), norm=norm))
fftn = _fftn_op("fftn", jnp.fft.fftn)
ifftn = _fftn_op("ifftn", jnp.fft.ifftn)
rfftn = _fftn_op("rfftn", jnp.fft.rfftn)
irfftn = _fftn_op("irfftn", jnp.fft.irfftn)


_INV_NORM = {"backward": "forward", "forward": "backward", "ortho": "ortho"}


def _hfftn(a, s=None, axes=None, norm="backward"):
    # Hermitian-input n-d FFT via the identity hfftn(a) =
    # irfftn(conj(a)) with the norm convention swapped (scipy.fft.hfftn
    # semantics; jnp only ships the 1-d hfft). reference: fft.py hfftn.
    return jnp.fft.irfftn(jnp.conj(a), s=s, axes=axes,
                          norm=_INV_NORM[norm])


def _ihfftn(a, s=None, axes=None, norm="backward"):
    return jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes,
                                  norm=_INV_NORM[norm]))


hfftn = _fftn_op("hfftn", _hfftn)
ihfftn = _fftn_op("ihfftn", _ihfftn)
hfft2 = _fftn_op("hfft2", lambda a, s, axes, norm: _hfftn(
    a, s=s, axes=axes or (-2, -1), norm=norm))
ihfft2 = _fftn_op("ihfft2", lambda a, s, axes, norm: _ihfftn(
    a, s=s, axes=axes or (-2, -1), norm=norm))


def fftfreq(n, d=1.0, dtype=None, name=None):
    from paddle_tpu.core.tensor import Tensor
    return Tensor._wrap(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from paddle_tpu.core.tensor import Tensor
    return Tensor._wrap(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return run_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return run_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
