"""paddle.framework equivalent: io (save/load), core shim, misc."""
from . import core  # noqa: F401
from .io import load, save  # noqa: F401


def get_default_dtype():
    from paddle_tpu.core.dtype import get_default_dtype as g
    return g()


def set_default_dtype(d):
    from paddle_tpu.core.dtype import set_default_dtype as s
    return s(d)


def in_dynamic_mode():
    return True
