"""paddle.base.core shim (reference: the pybind `libpaddle` module,
fluid/pybind/pybind.cc). Maps the commonly-touched core symbols onto the
TPU-native runtime: places, TCPStore, RNG generator, flags."""
from __future__ import annotations

from paddle_tpu.core.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, Place, TPUPlace,
    XPUPlace,
)
from paddle_tpu.core.flags import get_flags, set_flags  # noqa: F401
from paddle_tpu.core.generator import (  # noqa: F401
    Generator, default_generator,
)
from paddle_tpu.native import TCPStore, BlockingQueue  # noqa: F401


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(name="tpu"):
    return name in ("tpu",)


def get_cuda_device_count():
    return 0


def _get_paddle_place(place):
    return place


class VarDesc:
    class VarType:
        FP32 = "float32"
        FP16 = "float16"
        BF16 = "bfloat16"
        FP64 = "float64"
        INT32 = "int32"
        INT64 = "int64"
        BOOL = "bool"
        UINT8 = "uint8"
        INT8 = "int8"
