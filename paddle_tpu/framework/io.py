"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:773,
1020 — pickled state dicts). Tensors are stored as numpy arrays with dtype
preserved (bf16 via ml_dtypes round-trips through numpy natively)."""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_storable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            return Tensor._wrap(jnp.asarray(obj["data"]),
                                stop_gradient=obj.get("stop_gradient", True))
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_storable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_storable(obj, return_numpy)
