"""paddle.geometric equivalent: segment + message-passing ops
(reference: python/paddle/geometric over phi segment kernels).
TPU-native: jax.ops.segment_* (sorted-scatter XLA lowering)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor


def _nseg(ids):
    import numpy as np
    return int(np.asarray(ids._data).max()) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return run_op("segment_sum",
                  lambda d, i: jax.ops.segment_sum(
                      d, i.astype(jnp.int32), num_segments=n),
                  data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    def f(d, i):
        i = i.astype(jnp.int32)
        s = jax.ops.segment_sum(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(d[..., :1]), i,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1)
    return run_op("segment_mean", f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return run_op("segment_max",
                  lambda d, i: jax.ops.segment_max(
                      d, i.astype(jnp.int32), num_segments=n),
                  data, segment_ids)


def segment_min(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return run_op("segment_min",
                  lambda d, i: jax.ops.segment_min(
                      d, i.astype(jnp.int32), num_segments=n),
                  data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x at src, scatter-reduce at dst (graph message passing)."""
    import numpy as np
    n = out_size or (int(np.asarray(dst_index._data).max()) + 1)
    def f(a, src, dst):
        msgs = jnp.take(a, src.astype(jnp.int32), axis=0)
        red = {"sum": jax.ops.segment_sum, "mean": None,
               "max": jax.ops.segment_max,
               "min": jax.ops.segment_min}[reduce_op]
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst.astype(jnp.int32),
                                    num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0], 1), msgs.dtype),
                dst.astype(jnp.int32), num_segments=n)
            return s / jnp.maximum(cnt, 1)
        return red(msgs, dst.astype(jnp.int32), num_segments=n)
    return run_op("send_u_recv", f, x, src_index, dst_index)


def send_ue_recv(x, e, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    import numpy as np
    n = out_size or (int(np.asarray(dst_index._data).max()) + 1)
    def f(a, ew, src, dst):
        msgs = jnp.take(a, src.astype(jnp.int32), axis=0)
        combine = {"add": lambda m, w: m + w, "sub": lambda m, w: m - w,
                   "mul": lambda m, w: m * w, "div": lambda m, w: m / w}
        msgs = combine[message_op](msgs, ew)
        d = dst.astype(jnp.int32)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, d, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0], 1), msgs.dtype), d, num_segments=n)
            return s / jnp.maximum(cnt, 1)
        red = {"sum": jax.ops.segment_sum, "add": jax.ops.segment_sum,
               "max": jax.ops.segment_max,
               "min": jax.ops.segment_min}[reduce_op]
        return red(msgs, d, num_segments=n)
    return run_op("send_ue_recv", f, x, e, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message op(x[src], y[dst]) with NO reduce (reference
    geometric/message_passing/send_recv.py:413)."""
    def f(a, b, src, dst):
        xs = jnp.take(a, src.astype(jnp.int32), axis=0)
        yd = jnp.take(b, dst.astype(jnp.int32), axis=0)
        return {"add": xs + yd, "sub": xs - yd, "mul": xs * yd,
                "div": xs / yd}[message_op]
    return run_op("send_uv", f, x, y, src_index, dst_index)


# ------------------------------------------------------------------
# Graph sampling / reindex: host-side input-pipeline ops on a CSC
# graph (reference geometric/sampling/neighbors.py:30, reindex.py:32 —
# phi graph_sample_neighbors / reindex_graph kernels). On TPU the
# sampling stage lives in the host data pipeline, so these are numpy.
# ------------------------------------------------------------------

def _np1d(t):
    import numpy as np
    return np.asarray(t._data if isinstance(t, Tensor) else t).reshape(-1)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    import numpy as np
    if return_eids and eids is None:
        raise ValueError("`eids` should not be None if `return_eids` is "
                         "True.")
    r, cp, nodes = _np1d(row), _np1d(colptr), _np1d(input_nodes)
    ev = _np1d(eids) if eids is not None else None
    rng = np.random.default_rng()
    neigh, cnt, out_eids = [], [], []
    for n in nodes.tolist():
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(lo, hi)
        else:
            idx = lo + rng.choice(deg, size=sample_size, replace=False)
        neigh.append(r[idx])
        cnt.append(len(idx))
        if ev is not None:
            out_eids.append(ev[idx])
    out_n = Tensor(np.concatenate(neigh) if neigh
                   else np.empty(0, r.dtype))
    out_c = Tensor(np.asarray(cnt, np.int32))
    if return_eids:
        return out_n, out_c, Tensor(
            np.concatenate(out_eids) if out_eids else np.empty(0, r.dtype))
    return out_n, out_c


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    import numpy as np
    if return_eids and eids is None:
        raise ValueError("`eids` should not be None if `return_eids` is "
                         "True.")
    r, cp, nodes = _np1d(row), _np1d(colptr), _np1d(input_nodes)
    w = _np1d(edge_weight).astype(np.float64)
    ev = _np1d(eids) if eids is not None else None
    rng = np.random.default_rng()
    neigh, cnt, out_eids = [], [], []
    for n in nodes.tolist():
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if deg == 0:
            cnt.append(0)
            continue
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(lo, hi)
        else:
            p = w[lo:hi] / w[lo:hi].sum()
            idx = lo + rng.choice(deg, size=sample_size, replace=False, p=p)
        neigh.append(r[idx])
        cnt.append(len(idx))
        if ev is not None:
            out_eids.append(ev[idx])
    out_n = Tensor(np.concatenate(neigh) if neigh
                   else np.empty(0, r.dtype))
    out_c = Tensor(np.asarray(cnt, np.int32))
    if return_eids:
        return out_n, out_c, Tensor(
            np.concatenate(out_eids) if out_eids else np.empty(0, r.dtype))
    return out_n, out_c


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    import numpy as np
    xs, ns, cs = _np1d(x), _np1d(neighbors), _np1d(count)
    remap = {int(v): i for i, v in enumerate(xs.tolist())}
    out_nodes = list(xs.tolist())
    src = np.empty(len(ns), xs.dtype)
    for i, v in enumerate(ns.tolist()):
        j = remap.get(int(v))
        if j is None:
            j = len(out_nodes)
            remap[int(v)] = j
            out_nodes.append(int(v))
        src[i] = j
    dst = np.repeat(np.arange(len(cs), dtype=xs.dtype), cs)
    return (Tensor(src), Tensor(dst),
            Tensor(np.asarray(out_nodes, xs.dtype)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """reindex over a list of per-edge-type neighbor/count tensors
    sharing one node renumbering (reference reindex.py:heter)."""
    import numpy as np
    xs = _np1d(x)
    remap = {int(v): i for i, v in enumerate(xs.tolist())}
    out_nodes = list(xs.tolist())
    srcs, dsts = [], []
    for ns_t, cs_t in zip(neighbors, count):
        ns, cs = _np1d(ns_t), _np1d(cs_t)
        src = np.empty(len(ns), xs.dtype)
        for i, v in enumerate(ns.tolist()):
            j = remap.get(int(v))
            if j is None:
                j = len(out_nodes)
                remap[int(v)] = j
                out_nodes.append(int(v))
            src[i] = j
        srcs.append(Tensor(src))
        dsts.append(Tensor(np.repeat(np.arange(len(cs), dtype=xs.dtype),
                                     cs)))
    return srcs, dsts, Tensor(np.asarray(out_nodes, xs.dtype))
