"""paddle.geometric equivalent: segment + message-passing ops
(reference: python/paddle/geometric over phi segment kernels).
TPU-native: jax.ops.segment_* (sorted-scatter XLA lowering)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor


def _nseg(ids):
    import numpy as np
    return int(np.asarray(ids._data).max()) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return run_op("segment_sum",
                  lambda d, i: jax.ops.segment_sum(
                      d, i.astype(jnp.int32), num_segments=n),
                  data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    def f(d, i):
        i = i.astype(jnp.int32)
        s = jax.ops.segment_sum(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(d[..., :1]), i,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1)
    return run_op("segment_mean", f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return run_op("segment_max",
                  lambda d, i: jax.ops.segment_max(
                      d, i.astype(jnp.int32), num_segments=n),
                  data, segment_ids)


def segment_min(data, segment_ids, name=None):
    n = _nseg(segment_ids)
    return run_op("segment_min",
                  lambda d, i: jax.ops.segment_min(
                      d, i.astype(jnp.int32), num_segments=n),
                  data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x at src, scatter-reduce at dst (graph message passing)."""
    import numpy as np
    n = out_size or (int(np.asarray(dst_index._data).max()) + 1)
    def f(a, src, dst):
        msgs = jnp.take(a, src.astype(jnp.int32), axis=0)
        red = {"sum": jax.ops.segment_sum, "mean": None,
               "max": jax.ops.segment_max,
               "min": jax.ops.segment_min}[reduce_op]
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst.astype(jnp.int32),
                                    num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0], 1), msgs.dtype),
                dst.astype(jnp.int32), num_segments=n)
            return s / jnp.maximum(cnt, 1)
        return red(msgs, dst.astype(jnp.int32), num_segments=n)
    return run_op("send_u_recv", f, x, src_index, dst_index)


def send_ue_recv(x, e, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    import numpy as np
    n = out_size or (int(np.asarray(dst_index._data).max()) + 1)
    def f(a, ew, src, dst):
        msgs = jnp.take(a, src.astype(jnp.int32), axis=0)
        combine = {"add": lambda m, w: m + w, "sub": lambda m, w: m - w,
                   "mul": lambda m, w: m * w, "div": lambda m, w: m / w}
        msgs = combine[message_op](msgs, ew)
        d = dst.astype(jnp.int32)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, d, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0], 1), msgs.dtype), d, num_segments=n)
            return s / jnp.maximum(cnt, 1)
        red = {"sum": jax.ops.segment_sum, "add": jax.ops.segment_sum,
               "max": jax.ops.segment_max,
               "min": jax.ops.segment_min}[reduce_op]
        return red(msgs, d, num_segments=n)
    return run_op("send_ue_recv", f, x, e, src_index, dst_index)
