"""High-level API (reference: python/paddle/hapi/model.py — Model :1082,
fit :1808, callbacks)."""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import _chaos
from paddle_tpu import training as _ftrain
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io import DataLoader
from paddle_tpu.metric import Metric
from paddle_tpu.observability import metrics as _met
from paddle_tpu.observability import training as _otrain


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                              else f"{k}: {v}"
                              for k, v in (logs or {}).items())
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                              else f"{k}: {v}"
                              for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done in {dt:.1f}s: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class FaultTolerantCheckpoint(Callback):
    """Preemption-safe periodic checkpointing with exact resume
    (ISSUE 15; reference posture: fleet/elastic auto-resume).

    Every ``every_n_steps`` completed optimizer steps — and, crucially,
    when a preemption notice arrives (SIGTERM by default, or the
    ``train.preempt`` chaos site in drills) — the callback flushes a
    COMMITTED checkpoint (``_COMMITTED.json`` protocol) holding model
    + optimizer tensors, the default-Generator RNG state, and the
    dataloader position, then stops ``fit`` cleanly at the step
    boundary. On the next run, ``on_train_begin`` resumes from
    ``latest_committed(root)``: parameters restore in place and the
    dataloader fast-forwards so the run consumes the EXACT remaining
    data order (proven bitwise by tests/test_train_robustness.py).

    Pass the SAME ``DataLoader`` instance to both ``fit`` and this
    callback (and give it a ``seed`` for reproducible shuffling) —
    the loader's position is part of the checkpoint."""

    def __init__(self, root, every_n_steps=1, dataloader=None,
                 scaler=None, resume=True, install_signal_handler=True,
                 signals=None, keep_last=None):
        self.root = root
        self.every_n_steps = int(every_n_steps)
        self.dataloader = dataloader
        self.scaler = scaler
        self.resume = resume
        self.keep_last = keep_last
        self.global_step = 0          # completed optimizer steps
        self.fit_epoch = 0            # fit epoch currently running
        self.resumed_from = None
        self.preempted = False
        self.stopped = False
        self._handler = None
        if install_signal_handler:
            import signal as _signal
            sigs = signals if signals is not None else (_signal.SIGTERM,)
            self._handler = _ftrain.PreemptionHandler(sigs)

    def on_train_begin(self, logs=None):
        os.makedirs(self.root, exist_ok=True)
        # a REUSED callback (the natural resume-retry pattern: call
        # fit again with the same instance) must not carry a consumed
        # preemption notice into the next run — it would stop every
        # subsequent fit after one batch
        self.stopped = False
        self.preempted = False
        if self.resume:
            # resume BEFORE installing the signal handler: a failed
            # load (seed mismatch, corrupt checkpoint) must not leave
            # a flag-only SIGTERM handler installed on an abandoned
            # run — it would swallow every later real preemption
            meta = _ftrain.load_train_checkpoint(
                self.root, self.model.network, self.model._optimizer,
                self.dataloader, self.scaler)
            if meta is not None:
                self.global_step = int(meta["step"])
                self.fit_epoch = int(meta.get("epoch", 0))
                self.resumed_from = meta["path"]
                # chaos/step-guard contexts key on the GLOBAL step,
                # and fit's epoch BUDGET must not re-run completed
                # epochs (the loader position covers the partial one)
                self.model._steps_seen = self.global_step
                self.model._initial_epoch = self.fit_epoch
                self._normalize_epoch_boundary()
        if self._handler is not None:
            self._handler.triggered = False
            self._handler.install()

    def _normalize_epoch_boundary(self):
        """A checkpoint flushed at an epoch's FINAL batch restores as
        (epoch e, all batches served): re-entering epoch e would yield
        zero batches but still fire on_epoch_end/eval a second time
        (double-stepping epoch-wise LR schedulers, double-counting
        early-stop patience). Normalize to the equivalent position —
        the start of epoch e+1 — for both the loader and fit's epoch
        budget."""
        dl = self.dataloader
        if dl is None or not hasattr(dl, "state_dict"):
            return
        st = dl.state_dict()
        try:
            per_epoch = len(dl)
        except TypeError:
            return
        if per_epoch and st["batches_served"] >= per_epoch:
            dl.set_state_dict({"epoch": st["epoch"] + 1,
                               "batches_served": 0,
                               "seed": st["seed"]})
            self.fit_epoch += 1
            self.model._initial_epoch = self.fit_epoch

    def on_epoch_begin(self, epoch, logs=None):
        self.fit_epoch = int(epoch)

    def on_train_batch_end(self, step, logs=None):
        gs = self.global_step = self.global_step + 1
        preempt = self._handler is not None and self._handler.triggered
        try:
            _chaos.hit("train.preempt", step=gs)
        except _chaos.ChaosError:
            preempt = True       # injected preemption notice (drills)
        if preempt:
            self._flush(gs)
            if _met._ENABLED:
                _met.REGISTRY.counter("train.preemptions").inc()
            self.preempted = True
            self.stopped = True           # fit stops at this batch
            self.model.stop_training = True
            return
        if self.every_n_steps and gs % self.every_n_steps == 0:
            self._flush(gs)

    def on_train_end(self, logs=None):
        if self._handler is not None:
            self._handler.restore()

    def _flush(self, gs):
        _ftrain.save_train_checkpoint(
            self.root, gs, self.model.network, self.model._optimizer,
            self.dataloader, self.scaler, epoch=self.fit_epoch)
        if self.keep_last:
            self._prune()

    def _prune(self):
        import shutil
        from paddle_tpu.distributed import checkpoint as dc
        committed = [d for d in sorted(os.listdir(self.root))
                     if d.startswith("step_")
                     and dc.is_committed(os.path.join(self.root, d))]
        for d in committed[:-self.keep_last]:
            shutil.rmtree(os.path.join(self.root, d),
                          ignore_errors=True)


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.stopped = False
        self.mode = "min" if mode == "auto" and "loss" in monitor else mode

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        better = self.best is None or (
            cur < self.best - self.min_delta if self.mode == "min"
            else cur > self.best + self.min_delta)
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler per batch/epoch (reference
    hapi/callbacks.py LRSchedulerCallback)."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    """Reduce LR when a metric has stopped improving (reference
    hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor='loss', factor=0.1, patience=10, verbose=1,
                 mode='auto', min_delta=1e-4, cooldown=0, min_lr=0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = "min" if mode == "auto" and "loss" in monitor else mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        better = self.best is None or (
            cur < self.best - self.min_delta if self.mode == "min"
            else cur > self.best + self.min_delta)
        if better:
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    new_lr = max(opt.get_lr() * self.factor, self.min_lr)
                    opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr -> {new_lr:g}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar logging callback (reference hapi/callbacks.py VisualDL;
    the visualdl package is not in this image, so scalars go to a
    jsonl file under log_dir)."""

    def __init__(self, log_dir):
        self.log_dir = log_dir
        self._f = None

    def _write(self, tag, logs, step):
        import json
        if self._f is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._f = open(os.path.join(self.log_dir, "scalars.jsonl"),
                           "a")
        for k, v in (logs or {}).items():
            try:
                v = float(v[0] if isinstance(v, (list, tuple)) else v)
            except (TypeError, ValueError):
                continue
            self._f.write(json.dumps(
                {"tag": f"{tag}/{k}", "value": v, "step": step}) + "\n")
        self._f.flush()

    def on_train_batch_end(self, step, logs=None):
        self._write("train", logs, step)

    def on_eval_end(self, logs=None):
        self._write("eval", logs, 0)

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
            self._f = None


class WandbCallback(Callback):
    """Weights&Biases logging (reference hapi/callbacks.py
    WandbCallback); gated on the wandb package being importable."""

    def __init__(self, project=None, run=None, **kwargs):
        try:
            import wandb
            self.wandb = wandb
        except ImportError:
            raise ImportError(
                "WandbCallback requires the `wandb` package, which is "
                "not installed in this environment")
        self.project = project
        self.kwargs = kwargs
        self.run = run

    def on_train_begin(self, logs=None):
        if self.run is None:
            self.run = self.wandb.init(project=self.project, **self.kwargs)

    def on_train_batch_end(self, step, logs=None):
        self.run.log({k: v for k, v in (logs or {}).items()
                      if isinstance(v, (int, float))})

    def on_train_end(self, logs=None):
        if self.run is not None:
            self.run.finish()


class Model:
    """Keras-like trainer (reference hapi/model.py:1082)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._step_guard = None
        self._watchdog = None
        self._steps_seen = 0
        self._initial_epoch = 0

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, step_guard=None, watchdog=None):
        """``step_guard``: a ``training.StepGuard`` giving train_batch
        skip-step semantics on non-finite loss/grads plus the
        consecutive-bad circuit breaker. ``watchdog``: a
        ``distributed.watchdog.TrainStepWatchdog`` armed around every
        step — a stalled step aborts with a ``TrainHangError``
        straggler report instead of hanging silently."""
        self._optimizer = optimizer
        self._loss = loss
        self._step_guard = step_guard
        self._watchdog = watchdog
        if metrics is None:
            self._metrics = []
        else:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]

    # --------------------------------------------------------------- steps
    def train_batch(self, inputs, labels=None):
        self.network.train()
        # unconditional: enabling metrics mid-step must not record a
        # dt measured from 0.0 (perf_counter is ~ns, no cost to skip)
        t0 = time.perf_counter()
        step_idx = self._steps_seen
        wd = self._watchdog
        if wd is not None:
            wd.step_begin(step_idx)
        skipped = False
        try:
            _chaos.hit("train.step", step=step_idx)
            inputs = inputs if isinstance(inputs, (list, tuple)) \
                else [inputs]
            labels = labels if labels is None or isinstance(
                labels, (list, tuple)) else [labels]
            outputs = self.network(*inputs)
            losses = self._loss(outputs, *labels) if labels is not None \
                else outputs
            loss = losses if isinstance(losses, Tensor) else sum(losses)
            loss.backward()
            guard = self._step_guard
            if guard is not None and not guard.pre_step(
                    loss, self._optimizer, step=step_idx):
                # skip-step: non-finite loss/grads — drop this update,
                # keep the run alive (pre_step's circuit breaker
                # aborts when bad steps persist)
                self._optimizer.clear_grad()
                skipped = True
            else:
                self._optimizer.step()
                self._optimizer.clear_grad()
            loss_val = float(loss)
        except KeyboardInterrupt:
            # translate on the abort TOKEN, not trip state: a
            # late-landing watchdog SIGINT (next step already re-armed)
            # is still a hang abort; a genuine ctrl-C never carries a
            # token and propagates
            err = wd.consume_abort() if wd is not None else None
            if err is not None:
                raise err from None
            raise
        finally:
            if wd is not None:
                wd.step_end()
        self._steps_seen += 1
        if skipped:
            # not an optimizer step: keep MFU/step-time clean and the
            # metric accumulators unpolluted by the bad batch
            metrics = [m.accumulate() for m in self._metrics]
            return ([loss_val], metrics) if metrics else [loss_val]
        if _met._ENABLED:
            # timed AFTER the float(loss) device sync: the step's true
            # end — timing only the async dispatch would report
            # impossible throughput on a real accelerator
            self._record_step_metrics(time.perf_counter() - t0, inputs)
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, *labels)
                     if labels is not None else m.compute(outputs))
            metrics.append(m.accumulate())
        return ([loss_val], metrics) if metrics else [loss_val]

    @staticmethod
    def _record_step_metrics(dt, inputs):
        """One train step into the observability registry: step time,
        samples/s, and — for token batches ([B, S] integer ids) —
        tokens/s feeding the MFU gauge when
        observability.training.configure() declared the model cost."""
        samples = tokens = None
        x = inputs[0] if inputs else None
        if isinstance(x, Tensor) and x.ndim >= 1:
            samples = int(x.shape[0])
            import numpy as _np
            if x.ndim >= 2 and _np.issubdtype(x._data.dtype, _np.integer):
                tokens = int(x.shape[0]) * int(x.shape[1])
        _otrain.record_step(dt, samples=samples, tokens=tokens)

    @paddle.no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(
            labels, (list, tuple)) else [labels]
        outputs = self.network(*inputs)
        losses = self._loss(outputs, *labels) if self._loss and \
            labels is not None else None
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, *labels)
                     if labels is not None else m.compute(outputs))
            metrics.append(m.accumulate())
        loss_val = [float(losses if isinstance(losses, Tensor)
                          else sum(losses))] if losses is not None else []
        return (loss_val, metrics) if metrics else loss_val

    @paddle.no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        return out

    # ----------------------------------------------------------------- fit
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=1, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(ProgBarLogger(log_freq, verbose))
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        for cb in cbs:
            cb.set_model(self)
        it = 0
        history = {"loss": []}
        stop = False
        self.stop_training = False
        try:
            # on_train_begin INSIDE the try: a callback that fails
            # here (e.g. a refused resume) must still get the
            # finally's on_train_end cleanup — signal handlers and
            # file sinks cannot leak on a failed start
            for cb in cbs:
                cb.on_train_begin()
            # a resume (FaultTolerantCheckpoint) sets _initial_epoch
            # so the epoch BUDGET carries across a restart: completed
            # epochs are not re-run (the dataloader's own restored
            # position covers the partial one). One-shot: consumed
            # here, reset for later fits.
            start_epoch = self._initial_epoch
            self._initial_epoch = 0
            for epoch in range(start_epoch, epochs):
                for m in self._metrics:
                    m.reset()
                for cb in cbs:
                    cb.on_epoch_begin(epoch)
                logs = {}
                data_iter = iter(loader)
                step = 0
                try:
                    while True:
                        _chaos.hit("train.data_fetch", epoch=epoch,
                                   step=it)
                        try:
                            batch = next(data_iter)
                        except StopIteration:
                            break
                        for cb in cbs:
                            cb.on_train_batch_begin(step)
                        batch = batch if isinstance(batch,
                                                    (list, tuple)) \
                            else [batch]
                        ins, labs = batch[:-1], batch[-1:]
                        if len(batch) == 1:
                            ins, labs = batch, None
                        res = self.train_batch(list(ins), labs)
                        loss_val = res[0][0] if isinstance(res, tuple) \
                            else res[0]
                        logs = {"loss": loss_val}
                        if isinstance(res, tuple):
                            for m, v in zip(self._metrics, res[1]):
                                logs[m.name()] = v
                        history["loss"].append(loss_val)
                        for cb in cbs:
                            cb.on_train_batch_end(step, logs)
                        it += 1
                        step += 1
                        if self.stop_training or any(
                                getattr(cb, "stopped", False)
                                for cb in cbs):
                            # preemption / early stop honored at the
                            # step boundary, mid-epoch
                            stop = True
                            break
                        if num_iters is not None and it >= num_iters:
                            # unlike the preemption stop above, a
                            # num_iters exit ends the RUN (never
                            # resumed back into this epoch), so the
                            # long-standing fire-epoch-end-after-break
                            # behavior cannot double-step anything —
                            # kept for compatibility
                            break
                finally:
                    # deterministic release on every exit (preempt,
                    # crash, num_iters): an abandoned loader iterator
                    # must unwind its prefetch machinery now, not at
                    # a later GC
                    close = getattr(data_iter, "close", None)
                    if close is not None:
                        close()
                if stop:
                    # the epoch was cut short (preemption / stop flag):
                    # its end-of-epoch hooks belong to the RESUMED run
                    # — firing them here would double-step epoch-wise
                    # LR schedulers and early-stop patience
                    break
                for cb in cbs:
                    cb.on_epoch_end(epoch, logs)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_data,
                                              batch_size=batch_size,
                                              verbose=0)
                    for cb in cbs:
                        cb.on_eval_end(eval_logs)
                if any(getattr(cb, "stopped", False) for cb in cbs):
                    break
                if num_iters is not None and it >= num_iters:
                    break
        except KeyboardInterrupt:
            # a watchdog abort whose SIGINT lands between steps (the
            # step completed while the monitor was dumping) must still
            # surface as a hang report, not a bare ctrl-C — the abort
            # token distinguishes the two
            wd = self._watchdog
            err = wd.consume_abort() if wd is not None else None
            if err is not None:
                raise err from None
            raise
        finally:
            # ALWAYS — even when an attempt crashes mid-loop: a leaked
            # SIGTERM handler on a dead callback would swallow the next
            # attempt's preemption notice, and file-backed callbacks
            # must close their sinks. Per-callback isolation: one sink
            # failing to close must neither skip another's cleanup nor
            # mask the in-flight training exception.
            import sys as _sys
            in_flight = _sys.exc_info()[0] is not None
            cleanup_err = None
            for cb in cbs:
                try:
                    cb.on_train_end()
                except Exception as ce:  # noqa: BLE001
                    if cleanup_err is None:
                        cleanup_err = ce
                    import traceback
                    traceback.print_exc()
            if cleanup_err is not None and not in_flight:
                raise cleanup_err
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size)
        for m in self._metrics:
            m.reset()
        total_loss, n = 0.0, 0
        for step, batch in enumerate(loader):
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            ins, labs = batch[:-1], batch[-1:]
            res = self.eval_batch(list(ins), labs)
            losses = res[0] if isinstance(res, tuple) else res
            if losses:
                total_loss += losses[0]
                n += 1
            if num_iters is not None and step + 1 >= num_iters:
                break
        logs = {"loss": [total_loss / max(n, 1)]}
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            outs.append(self.predict_batch(batch[:1]))
        return outs

    # ------------------------------------------------------------- persist
    def save(self, path, training=True):
        paddle.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(paddle.load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(paddle.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return paddle.summary(self.network)
