"""paddle.hub (reference: python/paddle/hapi/hub.py): load models from a
local hubconf.py (the github/gitee sources need egress; local dirs work)."""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source="local", force_reload=False):
    if source != "local":
        raise RuntimeError("no network egress: only source='local' "
                           "is supported")
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, *args, source="local", force_reload=False,
         **kwargs):
    if source != "local":
        raise RuntimeError("no network egress: only source='local' "
                           "is supported")
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model)(*args, **kwargs)


__all__ = ["list", "help", "load"]
