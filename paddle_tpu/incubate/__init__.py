"""paddle.incubate equivalent — the fused-op functional surface
(reference: python/paddle/incubate/nn/functional/*: fused_rms_norm,
fused_rotary_position_embedding, swiglu, fused_moe, fused_linear,
masked_multihead_attention, variable_length_memory_efficient_attention).

On TPU these are XLA-fused jnp graphs or Pallas kernels; keeping the
incubate names gives drop-in parity for reference model code.
"""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from . import distributed  # noqa: F401
from . import multiprocessing  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
