"""paddle.incubate equivalent — the fused-op functional surface
(reference: python/paddle/incubate/nn/functional/*: fused_rms_norm,
fused_rotary_position_embedding, swiglu, fused_moe, fused_linear,
masked_multihead_attention, variable_length_memory_efficient_attention).

On TPU these are XLA-fused jnp graphs or Pallas kernels; keeping the
incubate names gives drop-in parity for reference model code.
"""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from . import distributed  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import operators  # noqa: F401
from . import jit  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

# top-level incubate names (reference incubate/__init__.py __all__)
from paddle_tpu.geometric import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum)
from paddle_tpu.ops.extra import (  # noqa: F401
    fused_softmax_mask as softmax_mask_fuse,
    fused_softmax_mask_upper_triangle as softmax_mask_fuse_upper_triangle,
)
from .jit import inference  # noqa: F401
from .nn.loss import identity_loss  # noqa: F401
from .operators import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv)

__all__ = [
    'LookAhead', 'ModelAverage', 'graph_khop_sampler', 'graph_reindex',
    'graph_sample_neighbors', 'graph_send_recv', 'identity_loss',
    'inference', 'segment_max', 'segment_mean', 'segment_min',
    'segment_sum', 'softmax_mask_fuse',
    'softmax_mask_fuse_upper_triangle',
]
