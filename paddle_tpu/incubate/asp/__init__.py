"""paddle.incubate.asp equivalent (reference: incubate/asp/asp.py —
2:4 structured sparsity: prune_model magnitude masks + an optimizer
wrapper that re-applies masks after each step).

TPU framing: the MXU has no N:M sparse mode, so ASP here preserves the
*workflow* (masks, pruning, mask-preserving training) with dense
masked tensors — the capability (training a 2:4-sparse model) ports,
the speedup is GPU-hardware-specific."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers",
           "OptimizerWithSparsityGuarantee"]

_excluded: Dict[int, List[str]] = {}


def calculate_density(x) -> float:
    """reference asp.py calculate_density."""
    a = np.asarray(x._data if hasattr(x, "_data") else x)
    return float(np.count_nonzero(a)) / max(a.size, 1)


def _mask_2_4(w: np.ndarray) -> np.ndarray:
    """Keep the 2 largest-|w| of every 4 along the last dim (the n=2
    m=4 pattern of reference get_mask_2d_best / 1d)."""
    shape = w.shape
    flat = w.reshape(-1)
    pad = (-len(flat)) % 4
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = np.abs(flat.reshape(-1, 4))
    order = np.argsort(groups, axis=1)
    mask = np.ones_like(groups, bool)
    np.put_along_axis(mask, order[:, :2], False, axis=1)
    mask = mask.reshape(-1)
    if pad:
        mask = mask[:-pad]
    return mask.reshape(shape)


def set_excluded_layers(param_names, main_program=None):
    _excluded.setdefault(0, []).extend(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _prunable(layer, p):
    from paddle_tpu import nn
    if p.name and any(p.name.startswith(e) or e in p.name
                      for e in _excluded.get(0, [])):
        return False
    return isinstance(layer, (nn.Linear,)) and p.ndim == 2


def prune_model(model, n=2, m=4, mask_algo='mask_1d', with_mask=True):
    """Apply 2:4 magnitude masks to every prunable weight (reference
    asp.py:319). The mask is stored ON the parameter (`p._asp_mask`) —
    an id()-keyed registry would mis-apply stale masks when python
    recycles object ids across models."""
    masks = {}
    for layer in model.sublayers(include_self=True):
        w = getattr(layer, "weight", None)
        if w is None or not _prunable(layer, w):
            continue
        wn = np.asarray(w._data, np.float32)
        mask = _mask_2_4(wn)
        w._assign_array(jnp.asarray(wn * mask, w._data.dtype))
        w._asp_mask = mask
        masks[id(w)] = mask
    return masks


class OptimizerWithSparsityGuarantee:
    """reference asp.py:233: after each optimizer step, re-apply the
    masks so pruned entries stay zero through training."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self, *args, **kwargs):
        out = self._optimizer.step(*args, **kwargs)
        for p in self._optimizer._parameter_list:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._assign_array(p._data * jnp.asarray(mask,
                                                      p._data.dtype))
        return out

    def clear_grad(self, *a, **k):
        return self._optimizer.clear_grad(*a, **k)


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    return OptimizerWithSparsityGuarantee(optimizer)
