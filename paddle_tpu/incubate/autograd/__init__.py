"""paddle.incubate.autograd equivalent (reference: incubate/autograd —
functional higher-order AD: jvp/vjp/Jacobian/Hessian + prim switches).

TPU-native: these map directly onto jax's forward/reverse transforms —
the machinery the reference builds with prim ops and double-backward
is the compiler's native capability here."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "disable_prim",
           "enable_prim", "prim_enabled", "forward_grad", "grad"]


def _unwrap(xs):
    if isinstance(xs, (list, tuple)):
        return [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                for x in xs]
    return [xs._data if isinstance(xs, Tensor) else jnp.asarray(xs)]


def _wrap_like(arrs, template):
    outs = [Tensor._wrap(a) for a in arrs]
    if isinstance(template, (list, tuple)) or len(outs) > 1:
        return outs
    return outs[0]


def _fn_on_arrays(func):
    def f(*arrs):
        outs = func(*[Tensor._wrap(a) for a in arrs])
        if isinstance(outs, (list, tuple)):
            return tuple(o._data for o in outs)
        return outs._data
    return f


def jvp(func, xs, v=None):
    """Forward-mode: returns (outputs, JVP) (reference
    incubate/autograd/functional.py jvp)."""
    arrs = _unwrap(xs)
    tangents = _unwrap(v) if v is not None else \
        [jnp.ones_like(a) for a in arrs]
    out, tangent_out = jax.jvp(_fn_on_arrays(func), tuple(arrs),
                               tuple(tangents))
    single = not isinstance(out, tuple)
    outs = (out,) if single else out
    touts = (tangent_out,) if single else tangent_out
    return (_wrap_like(outs, xs), _wrap_like(touts, xs))


def vjp(func, xs, v=None):
    """Reverse-mode: returns (outputs, VJP) (reference vjp)."""
    arrs = _unwrap(xs)
    out, vjp_fn = jax.vjp(_fn_on_arrays(func), *arrs)
    single = not isinstance(out, tuple)
    outs = (out,) if single else out
    cotangents = _unwrap(v) if v is not None else \
        [jnp.ones_like(o) for o in outs]
    grads = vjp_fn(cotangents[0] if single else tuple(cotangents))
    return (_wrap_like(outs, xs), _wrap_like(list(grads), xs))


forward_grad = jvp


def grad(outputs, inputs, grad_outputs=None):
    from paddle_tpu.autograd import grad as _g
    return _g(outputs, inputs, grad_outputs)


class Jacobian:
    """Lazy row/col-sliceable Jacobian (reference
    incubate/autograd/functional.py Jacobian)."""

    def __init__(self, func, xs, is_batched=False):
        arrs = _unwrap(xs)
        jac = jax.jacrev(_fn_on_arrays(func), argnums=tuple(
            range(len(arrs))))(*arrs)
        leaves = jax.tree_util.tree_leaves(jac)
        self._jac = leaves[0] if len(leaves) == 1 else leaves
        self._is_batched = is_batched

    def __getitem__(self, idx):
        j = self._jac if not isinstance(self._jac, list) else self._jac[0]
        return Tensor._wrap(j[idx])

    @property
    def shape(self):
        j = self._jac if not isinstance(self._jac, list) else self._jac[0]
        return tuple(j.shape)


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        arrs = _unwrap(xs)
        h = jax.hessian(_fn_on_arrays(func))(*arrs)
        leaves = jax.tree_util.tree_leaves(h)
        self._h = leaves[0] if len(leaves) == 1 else leaves

    def __getitem__(self, idx):
        h = self._h if not isinstance(self._h, list) else self._h[0]
        return Tensor._wrap(h[idx])

    @property
    def shape(self):
        h = self._h if not isinstance(self._h, list) else self._h[0]
        return tuple(h.shape)


def enable_prim():
    from paddle_tpu.decomposition import enable_prim as ep
    ep(True)


def disable_prim():
    from paddle_tpu.decomposition import enable_prim as ep
    ep(False)


def prim_enabled():
    from paddle_tpu.decomposition import prim_enabled as pe
    return pe()
