"""paddle.incubate.distributed.models.moe namespace (reference:
incubate/distributed/models/moe/moe_layer.py:263 MoELayer + gate zoo;
implementation lives in paddle_tpu.models.moe — expert-parallel via
all-to-all over the dp axis, SURVEY §2.7 EP row)."""
from paddle_tpu.models.moe import (  # noqa: F401
    ExpertFFN, MoELayer, MoETransformerBlock, TopKGate,
)
from paddle_tpu.models.moe import TopKGate as GShardGate  # noqa: F401


class SwitchGate(TopKGate):
    """Switch routing is top-1 by definition (reference
    moe/gate/switch_gate.py)."""

    def __init__(self, hidden_size, num_experts, top_k=1,
                 capacity_factor=1.25):
        super().__init__(hidden_size, num_experts, 1, capacity_factor)
