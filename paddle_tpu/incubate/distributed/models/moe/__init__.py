"""paddle.incubate.distributed.models.moe namespace (reference:
incubate/distributed/models/moe/moe_layer.py:263 MoELayer + gate zoo;
implementation lives in paddle_tpu.models.moe — expert-parallel via
all-to-all over the dp axis, SURVEY §2.7 EP row)."""
from paddle_tpu.models.moe import (  # noqa: F401
    ExpertFFN, MoELayer, MoETransformerBlock, TopKGate,
)
from paddle_tpu.models.moe import TopKGate as GShardGate  # noqa: F401
from paddle_tpu.models.moe import TopKGate as SwitchGate  # noqa: F401
