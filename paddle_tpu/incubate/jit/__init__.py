"""incubate.jit (reference python/paddle/incubate/jit/: the
`inference` decorator compiles a Layer's forward / a function for
fast repeated inference).

TPU design: the reference rewrites the function into a Predictor with
TensorRT options; here the same decorator lowers onto the one true
compile path — paddle_tpu.jit.to_static under no_grad — whose executor
caches the compiled XLA executable per input shape. TRT-specific knobs
are accepted and ignored (XLA is the optimizing backend)."""
from __future__ import annotations

import functools

__all__ = ["inference"]


def inference(function=None, cache_static_model=False,
              save_model_dir=None, memory_pool_init_size_mb=1000,
              precision_mode="float32", switch_ir_optim=True,
              switch_ir_debug=False, enable_cinn=False,
              with_trt=False, trt_precision_mode="float32",
              trt_use_static=False, collect_shape=False,
              enable_new_ir=False, exp_enable_use_cutlass=False,
              delete_pass_lists=None, skip_prune_program=False):
    """Decorator: compile `function` (or a Layer's forward) for
    inference (reference incubate/jit/inference_decorator.py). All
    backend-tuning kwargs are accepted for parity; XLA compilation +
    the executable cache provide the optimization on TPU."""
    def wrap(fn):
        from paddle_tpu.jit import to_static
        import paddle_tpu

        forward = fn.forward if hasattr(fn, "forward") else fn
        compiled = to_static(forward)

        @functools.wraps(forward)
        def runner(*args, **kwargs):
            with paddle_tpu.no_grad():
                return compiled(*args, **kwargs)

        if hasattr(fn, "forward"):
            fn.forward = runner
            return fn
        return runner

    if function is not None:
        return wrap(function)
    return wrap
