"""paddle.incubate.multiprocessing equivalent (reference:
incubate/multiprocessing — mp with tensor-aware pickling over shared
memory). Device arrays pickle via host copies here (TPU HBM is not
process-sharable); the API shape is python multiprocessing's."""
from multiprocessing import *  # noqa: F401,F403
from multiprocessing import get_context, Process, Queue, Pipe  # noqa: F401

import copyreg

import numpy as np


def _rebuild_tensor(arr, stop_gradient, name):
    from paddle_tpu.core.tensor import Tensor
    t = Tensor(arr, stop_gradient=stop_gradient)
    if name is not None:
        t.name = name
    return t


def _reduce_tensor(t):
    """Pickle a Tensor as its host numpy copy, preserving
    stop_gradient and name (reference uses shared memory;
    cross-process device handles don't exist for TPU)."""
    return (_rebuild_tensor,
            (t.numpy(), t.stop_gradient, getattr(t, "name", None)))


def _install():
    from paddle_tpu.core.tensor import Tensor
    copyreg.pickle(Tensor, _reduce_tensor)


_install()
