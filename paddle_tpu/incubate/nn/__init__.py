from . import functional  # noqa: F401


class FusedLinear:
    def __new__(cls, in_features, out_features, bias_attr=None, **kw):
        from paddle_tpu.nn import Linear
        return Linear(in_features, out_features, bias_attr=bias_attr)
