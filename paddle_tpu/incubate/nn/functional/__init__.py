"""Fused functional ops (reference: paddle/incubate/nn/functional)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    """reference fused_rms_norm.py — returns (out, invvar) pair shape."""
    out = F.rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis)
    return out, None


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    shape = x.shape[begin_norm_axis:] if begin_norm_axis >= 0 else \
        x.shape[begin_norm_axis:]
    return F.layer_norm(x, shape, norm_weight, norm_bias, epsilon), None


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """reference fused_rotary_position_embedding: applies RoPE to q/k
    ([B, S, H, D] layout)."""
    from paddle_tpu.models.llama import apply_rotary_pos_emb
    outs = [apply_rotary_pos_emb(q)]
    outs.append(apply_rotary_pos_emb(k) if k is not None else None)
    outs.append(v)
    return tuple(outs)


def swiglu(x, y=None, name=None):
    """reference swiglu fused op: silu(x) * y (or split x in half)."""
    if y is not None:
        return run_op("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(a1) * a2
    return run_op("swiglu", f, x)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    def f(a, *b):
        if b:
            a = a + b[0]
        if act_method == "gelu":
            return jax.nn.gelu(a)
        if act_method in ("silu", "swish"):
            return jax.nn.silu(a)
        return jax.nn.relu(a)
    if bias is not None:
        return run_op("fused_bias_act", f, x, bias)
    return run_op("fused_bias_act", f, x)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(a, w, *b):
        wt = w.T if transpose_weight else w
        out = a @ wt
        if b:
            out = out + b[0]
        return out
    if bias is not None:
        return run_op("fused_linear", f, x, weight, bias)
    return run_op("fused_linear", f, x, weight)


def fused_linear_activation(x, weight, bias=None, activation="gelu",
                            **kw):
    out = fused_linear(x, weight, bias)
    return getattr(F, activation)(out)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_moe(x, gate_weight, ffn1_weight, ffn1_bias, ffn2_weight,
              ffn2_bias, top_k=2, norm_topk_prob=True, **kw):
    """reference fused_moe.py — dense-dispatch GShard MoE."""
    def f(a, gw, w1, b1, w2, b2):
        b, s, h = a.shape
        tokens = a.reshape(b * s, h)
        e = gw.shape[-1]
        probs = jax.nn.softmax(
            tokens.astype(jnp.float32) @ gw.astype(jnp.float32), -1)
        topv, topi = jax.lax.top_k(probs, top_k)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, -1, keepdims=True)
        disp = jnp.zeros_like(probs)
        comb = jnp.zeros_like(probs)
        for j in range(top_k):
            oh = jax.nn.one_hot(topi[:, j], e, dtype=probs.dtype)
            disp = disp + oh
            comb = comb + oh * topv[:, j:j + 1]
        xin = jnp.einsum("te,th->eth", disp.astype(a.dtype), tokens)
        hmid = jax.nn.gelu(jnp.einsum("eth,ehm->etm", xin, w1)
                           + b1[:, None])
        hout = jnp.einsum("etm,emh->eth", hmid, w2) + b2[:, None]
        out = jnp.einsum("te,eth->th", comb.astype(a.dtype), hout)
        return out.reshape(b, s, h)
    return run_op("fused_moe", f, x, gate_weight, ffn1_weight, ffn1_bias,
                  ffn2_weight, ffn2_bias)


def masked_multihead_attention(x, cache_kv=None, **kw):
    raise NotImplementedError(
        "decode-path masked_multihead_attention: use the KV-cache path in "
        "paddle_tpu.models.llama (LlamaModel with caches)")


def variable_length_memory_efficient_attention(query, key, value,
                                               seq_lens=None,
                                               kv_seq_lens=None,
                                               mask=None, scale=None,
                                               causal=False):
    out, _ = F.flash_attn_unpadded(query, key, value, seq_lens,
                                   kv_seq_lens, None, None, scale=scale,
                                   causal=causal) \
        if seq_lens is not None else (None, None)
    if out is None:
        return F.scaled_dot_product_attention(query, key, value, mask,
                                              is_causal=causal)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight, **kw):
    raise NotImplementedError(
        "use paddle_tpu.nn.MultiHeadAttention (XLA fuses the projections)")
