"""Fused functional ops (reference: paddle/incubate/nn/functional)."""
from __future__ import annotations

import builtins
import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    """reference fused_rms_norm.py — returns (out, invvar) pair shape."""
    out = F.rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis)
    return out, None


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    shape = x.shape[begin_norm_axis:]
    return F.layer_norm(x, shape, norm_weight, norm_bias, epsilon), None


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    theta=10000.0):
    """reference fused_rotary_position_embedding ([B, S, H, D] layout).

    sin/cos: optional precomputed tables [1, S, 1, D] (or [S, D]); when
    absent they are derived from `theta`. position_ids: optional [B, S]
    absolute positions (KV-cache decode). neox style rotates interleaved
    even/odd pairs; non-neox rotates the two half-splits.
    """
    d = q.shape[-1]
    seq = q.shape[1]
    if position_ids is not None:
        # table must cover the largest absolute position (KV-cache decode
        # passes positions beyond q's local seq length)
        pid_arr = position_ids._data if isinstance(position_ids, Tensor) \
            else jnp.asarray(position_ids)
        try:
            seq = builtins.max(seq, int(pid_arr.max()) + 1)
        except Exception:
            pass  # traced: caller must supply sin/cos tables instead

    def _tables():
        if sin is not None and cos is not None:
            s_t = sin._data if isinstance(sin, Tensor) else jnp.asarray(sin)
            c_t = cos._data if isinstance(cos, Tensor) else jnp.asarray(cos)
            if s_t.ndim == 2:                       # [S, D] -> [1, S, 1, D]
                s_t = s_t[None, :, None, :]
                c_t = c_t[None, :, None, :]
            return s_t, c_t
        inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        pos = jnp.arange(seq, dtype=jnp.float32)
        freqs = jnp.outer(pos, inv)                  # [S, D/2]
        if use_neox_rotary_style:
            full = jnp.repeat(freqs, 2, axis=-1)     # pair-interleaved
        else:
            full = jnp.concatenate([freqs, freqs], -1)   # half-split
        return (jnp.sin(full)[None, :, None, :],
                jnp.cos(full)[None, :, None, :])

    s_tab, c_tab = _tables()
    if position_ids is not None:
        pid = position_ids._data if isinstance(position_ids, Tensor) \
            else jnp.asarray(position_ids)
        # gather rows of the [1, S, 1, D] table per batch -> [B, S, 1, D]
        s_tab = jnp.take(s_tab[0, :, 0, :], pid, axis=0)[:, :, None, :]
        c_tab = jnp.take(c_tab[0, :, 0, :], pid, axis=0)[:, :, None, :]

    def rope(a):
        af = a.astype(jnp.float32)
        st = s_tab.astype(jnp.float32)
        ct = c_tab.astype(jnp.float32)
        if use_neox_rotary_style:
            x1, x2 = af[..., 0::2], af[..., 1::2]
            c_h, s_h = ct[..., 0::2], st[..., 0::2]
            o1 = x1 * c_h - x2 * s_h
            o2 = x2 * c_h + x1 * s_h
            out = jnp.stack([o1, o2], axis=-1).reshape(a.shape)
        else:
            half = a.shape[-1] // 2
            x1, x2 = af[..., :half], af[..., half:]
            c_h, s_h = ct[..., :half], st[..., :half]
            o1 = x1 * c_h - x2 * s_h
            o2 = x2 * c_h + x1 * s_h
            out = jnp.concatenate([o1, o2], axis=-1)
        return out.astype(a.dtype)

    outs = [run_op("fused_rope", rope, q)]
    outs.append(run_op("fused_rope", rope, k) if k is not None else None)
    outs.append(v)
    return tuple(outs)


def swiglu(x, y=None, name=None):
    """reference swiglu fused op: silu(x) * y (or split x in half)."""
    if y is not None:
        return run_op("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(a1) * a2
    return run_op("swiglu", f, x)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    def f(a, *b):
        if b:
            a = a + b[0]
        if act_method == "gelu":
            return jax.nn.gelu(a)
        if act_method in ("silu", "swish"):
            return jax.nn.silu(a)
        return jax.nn.relu(a)
    if bias is not None:
        return run_op("fused_bias_act", f, x, bias)
    return run_op("fused_bias_act", f, x)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(a, w, *b):
        wt = w.T if transpose_weight else w
        out = a @ wt
        if b:
            out = out + b[0]
        return out
    if bias is not None:
        return run_op("fused_linear", f, x, weight, bias)
    return run_op("fused_linear", f, x, weight)


def fused_linear_activation(x, weight, bias=None, activation="gelu",
                            **kw):
    out = fused_linear(x, weight, bias)
    return getattr(F, activation)(out)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_moe(x, gate_weight, ffn1_weight, ffn1_bias, ffn2_weight,
              ffn2_bias, top_k=2, norm_topk_prob=True, **kw):
    """reference fused_moe.py — dense-dispatch GShard MoE."""
    def f(a, gw, w1, b1, w2, b2):
        b, s, h = a.shape
        tokens = a.reshape(b * s, h)
        e = gw.shape[-1]
        probs = jax.nn.softmax(
            tokens.astype(jnp.float32) @ gw.astype(jnp.float32), -1)
        topv, topi = jax.lax.top_k(probs, top_k)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, -1, keepdims=True)
        disp = jnp.zeros_like(probs)
        comb = jnp.zeros_like(probs)
        for j in range(top_k):
            oh = jax.nn.one_hot(topi[:, j], e, dtype=probs.dtype)
            disp = disp + oh
            comb = comb + oh * topv[:, j:j + 1]
        xin = jnp.einsum("te,th->eth", disp.astype(a.dtype), tokens)
        hmid = jax.nn.gelu(jnp.einsum("eth,ehm->etm", xin, w1)
                           + b1[:, None])
        hout = jnp.einsum("etm,emh->eth", hmid, w2) + b2[:, None]
        out = jnp.einsum("te,eth->th", comb.astype(a.dtype), hout)
        return out.reshape(b, s, h)
    return run_op("fused_moe", f, x, gate_weight, ffn1_weight, ffn1_bias,
                  ffn2_weight, ffn2_bias)


def masked_multihead_attention(x, cache_kv=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype='default', out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Reference masked_multihead_attention.py — the single-token decode
    attention kernel. TPU-native: the paged GPU kernel becomes a
    static-shape program over a fixed-capacity cache (write via
    dynamic_update_slice + length-masked attention); see
    paddle_tpu/inference/decode.py for the full serving path.

    x: [B, 3*H*D] fused qkv for the current step; cache_kv:
    [2, B, H, max_seq, D]; sequence_lengths: [B] int32 (current lengths;
    defaults to full cache if omitted is not supported — pass lengths).
    Returns (out [B, H*D], new_cache_kv) (+ beam offset passthrough).
    """
    from paddle_tpu.inference.decode import masked_multihead_attention_impl
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv "
                         "[2, B, num_heads, max_seq_len, head_dim]")
    if sequence_lengths is None:
        raise ValueError(
            "pass sequence_lengths [B] int32: on TPU the cache is a "
            "fixed-capacity buffer, so valid lengths are explicit")
    if rotary_tensor is not None or use_neox_rotary_style:
        raise NotImplementedError(
            "custom rotary_tensor / neox-style rotary are not supported: "
            "only interleaved theta=1e4 RoPE (rotary_emb_dims>0) is "
            "implemented — apply custom rotary to x before the call")
    if src_mask is not None:
        raise NotImplementedError(
            "src_mask is not supported on the TPU decode path: causality "
            "comes from the cache length mask (mask lengths via "
            "sequence_lengths instead)")
    if out_scale is not None and out_scale > 0:
        raise NotImplementedError(
            "quantized (int8) attention output (out_scale>0) is not "
            "implemented — serve with inference int8 weight-only "
            "quantization instead")
    if compute_dtype not in ("default", "fp32", "float32"):
        raise NotImplementedError(
            f"compute_dtype={compute_dtype!r}: only fp32 compute is "
            "implemented (cast x/cache_kv for bf16 storage)")
    num_heads = cache_kv.shape[2]
    theta = None
    if rotary_emb_dims and rotary_emb_dims > 0:
        theta = 10000.0
    out, new_cache = masked_multihead_attention_impl(
        x, cache_kv, sequence_lengths, num_heads, rotary_theta=theta)
    if beam_cache_offset is not None:
        return out, new_cache, beam_cache_offset
    return out, new_cache


def variable_length_memory_efficient_attention(query, key, value,
                                               seq_lens=None,
                                               kv_seq_lens=None,
                                               mask=None, scale=None,
                                               causal=False):
    """Batched attention with per-sequence valid lengths (reference
    variable_length_memory_efficient_attention.py): q/k/v are
    [B, H, S, D]; seq_lens/kv_seq_lens are [B] actual lengths; padded key
    positions are masked out.
    """
    if seq_lens is None:
        return F.scaled_dot_product_attention(query, key, value, mask,
                                              is_causal=causal)
    if kv_seq_lens is None:
        kv_seq_lens = seq_lens

    def f(q, k, v, q_lens, k_lens, *rest):
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
        logits = logits.astype(jnp.float32)
        sq, sk = q.shape[2], k.shape[2]
        valid_k = jnp.arange(sk)[None, :] < k_lens[:, None]     # [B, Sk]
        m = valid_k[:, None, None, :]
        if causal:
            m = m & (jnp.arange(sq)[:, None]
                     >= jnp.arange(sk)[None, :])[None, None]
        if rest:
            m = m & (rest[0] if rest[0].dtype == jnp.bool_
                     else rest[0] > 0)
        logits = jnp.where(m, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        # zero padded query rows so they can't leak garbage downstream
        valid_q = jnp.arange(sq)[None, :] < q_lens[:, None]
        return out * valid_q[:, None, :, None].astype(out.dtype)

    args = [query, key, value, seq_lens, kv_seq_lens]
    if mask is not None:
        args.append(mask)
    return run_op("variable_length_attention", f, *args)


def fused_multi_head_attention(x, qkv_weight, linear_weight, **kw):
    raise NotImplementedError(
        "use paddle_tpu.nn.MultiHeadAttention (XLA fuses the projections)")


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """reference incubate fused_matmul_bias (cublasLt epilogue); XLA
    fuses the bias add into the GEMM on TPU."""
    import jax.numpy as jnp
    from paddle_tpu.core.dispatch import run_op

    def f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    args = (x, y) + ((bias,) if bias is not None else ())
    return run_op("fused_matmul_bias", f, *args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=
                      'upscale_in_train', ring_id=-1, name=None):
    """reference incubate fused_feedforward (fused FFN kernel): the
    pre/post-LN transformer FFN block as one XLA-fused graph."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops.linalg import matmul

    def ln(v, scale, bias, eps):
        return F.layer_norm(v, [v.shape[-1]], weight=scale, bias=bias,
                            epsilon=eps)

    residual = x
    if pre_layer_norm:
        x = ln(x, ln1_scale, ln1_bias, ln1_epsilon)
    h = matmul(x, linear1_weight)
    if linear1_bias is not None:
        h = h + linear1_bias
    h = getattr(F, activation)(h)
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = matmul(h, linear2_weight)
    if linear2_bias is not None:
        h = h + linear2_bias
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h
    if not pre_layer_norm:
        out = ln(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode='upscale_in_train', name=None):
    """reference incubate fused_bias_dropout_residual_layer_norm."""
    import paddle_tpu.nn.functional as F
    h = x if bias is None else x + bias
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    out = residual + h
    return F.layer_norm(out, [out.shape[-1]], weight=ln_scale,
                        bias=ln_bias, epsilon=ln_epsilon)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size,
                     name=None):
    """reference blha_get_max_len (block-attention helper): max
    encoder/decoder sequence lengths for kernel dispatch."""
    import jax.numpy as jnp
    from paddle_tpu.core.dispatch import run_op

    def f(enc, dec):
        return jnp.max(enc), jnp.max(dec)
    return run_op("blha_get_max_len", f, seq_lens_encoder,
                  seq_lens_decoder, n_outputs=2, differentiable=False)


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, **kwargs):
    """reference incubate block_multihead_attention (paged-KV inference
    attention). The paged-block layout is a GPU memory-management
    device; on TPU the cache lives as dense [B, S, H, D] arrays and XLA
    attention reads it directly — use
    paddle_tpu.nn.functional.scaled_dot_product_attention with a cache,
    or models/gpt.py's decode path."""
    raise NotImplementedError(
        "paged/block KV attention is a GPU memory-layout construct; on "
        "TPU use nn.functional.scaled_dot_product_attention over dense "
        "KV caches (models/*.py generate() paths)")


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, *args, **kwargs):
    """reference incubate fused_multi_transformer (single-kernel
    multi-layer inference transformer). The XLA analog is compiling the
    whole decode step with paddle_tpu.jit.to_static — one fused
    program; see models/gpt.py."""
    raise NotImplementedError(
        "compile the full decode step with paddle_tpu.jit.to_static "
        "instead: XLA produces the one fused program this kernel "
        "hand-writes on GPU")
