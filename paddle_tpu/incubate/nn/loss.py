"""incubate.nn.loss (reference python/paddle/incubate/nn/loss.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Tensor

_MODES = {"sum": 0, "mean": 1, "none": 2, 0: 0, 1: 1, 2: 2}


def identity_loss(x, reduction="none"):
    """Marks a tensor as the loss head and applies the reduction
    (reference incubate/nn/loss.py:36; 'sum'/'mean'/'none' or 0/1/2)."""
    if reduction not in _MODES:
        raise ValueError(f"reduction should be sum/mean/none, "
                         f"got {reduction!r}")
    mode = _MODES[reduction]
    t = x if isinstance(x, Tensor) else Tensor(x)
    if mode == 0:
        return run_op("identity_loss_sum", lambda a: jnp.sum(a), t)
    if mode == 1:
        return run_op("identity_loss_mean", lambda a: jnp.mean(a), t)
    return run_op("identity_loss", lambda a: a + jnp.zeros((), a.dtype), t)
