"""Legacy incubate graph operators (reference
python/paddle/incubate/operators/: graph_send_recv, graph_reindex,
graph_sample_neighbors, graph_khop_sampler) — thin wrappers over the
paddle.geometric implementations, kept for drop-in parity with
reference model code that predates the geometric namespace."""
from __future__ import annotations

import numpy as np

from paddle_tpu import geometric as _geo
from paddle_tpu.core.tensor import Tensor

__all__ = ["graph_send_recv", "graph_reindex",
           "graph_sample_neighbors", "graph_khop_sampler"]


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    return _geo.send_u_recv(x, src_index, dst_index,
                            reduce_op=pool_type, out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    return _geo.reindex_graph(x, neighbors, count, value_buffer,
                              index_buffer)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    return _geo.sample_neighbors(row, colptr, input_nodes,
                                 sample_size=sample_size, eids=eids,
                                 return_eids=return_eids,
                                 perm_buffer=perm_buffer)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference
    incubate/operators/graph_khop_sampler.py): per hop, sample
    `sample_sizes[k]` neighbors of the frontier, then reindex the
    union subgraph. Returns (edge_src, edge_dst, sample_index
    [, edge_eids])."""
    frontier = input_nodes
    all_neighbors, all_counts, all_eids = [], [], []
    seeds = _np(input_nodes)
    seen = list(seeds.tolist())
    seen_set = set(seen)
    for k, sz in enumerate(sample_sizes):
        res = _geo.sample_neighbors(row, colptr, frontier,
                                    sample_size=sz, eids=sorted_eids,
                                    return_eids=return_eids)
        if return_eids:
            neigh, cnt, eids_k = res
            all_eids.append(_np(eids_k))
        else:
            neigh, cnt = res
        all_neighbors.append(_np(neigh))
        all_counts.append(_np(cnt))
        # next frontier: newly discovered nodes
        new = [v for v in np.unique(_np(neigh)).tolist()
               if v not in seen_set]
        seen.extend(new)
        seen_set.update(new)
        frontier = Tensor(np.asarray(new, seeds.dtype)) if new else \
            Tensor(np.empty(0, seeds.dtype))
    neighbors = np.concatenate(all_neighbors) if all_neighbors else \
        np.empty(0, seeds.dtype)
    counts = np.concatenate(all_counts) if all_counts else \
        np.empty(0, np.int32)
    # counts are per sampled center, in hop order; centers are the
    # concatenation of per-hop frontiers, which is exactly `seen`
    # truncated to the number of count entries
    centers = np.asarray(seen[: len(counts)], seeds.dtype)
    src, dst, sample_index = _geo.reindex_graph(
        Tensor(centers), Tensor(neighbors), Tensor(counts))
    if return_eids:
        return src, dst, sample_index, Tensor(np.concatenate(all_eids))
    return src, dst, sample_index


def _np(t):
    if isinstance(t, Tensor):
        return np.asarray(t.numpy())
    return np.asarray(t)
