"""paddle.incubate.optimizer equivalent (reference:
incubate/optimizer — LookAhead and ModelAverage wrappers)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """reference incubate/optimizer/lookahead.py: fast optimizer steps k
    times, then slow weights interpolate toward fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step = 0
        self._slow = {}

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def step(self):
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k:
            return
        for p in self.inner_optimizer._parameter_list:
            slow = self._slow.get(id(p))
            if slow is None:
                slow = p._data
            slow = slow + self.alpha * (p._data - slow)
            self._slow[id(p)] = slow
            p._assign_array(slow)

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step
        return sd


class ModelAverage:
    """reference incubate/optimizer/modelaverage.py: maintain a running
    average of parameters; apply()/restore() swap it in and out for
    evaluation."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._params}
        self._count = 0
        self._backup = None

    def step(self):
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._data
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        if self._count == 0:
            return
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            p._assign_array((self._sum[id(p)] / self._count)
                            .astype(p._data.dtype))

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._assign_array(self._backup[id(p)])
        self._backup = None

from paddle_tpu.optimizer.gradient_merge import (  # noqa: F401
    GradientMergeOptimizer,
)
