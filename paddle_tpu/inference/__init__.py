"""paddle.inference equivalent (reference: AnalysisPredictor,
fluid/inference/api/analysis_predictor.h:105 — config + predictor with
zero-copy tensors, pass pipelines, TensorRT bridges).

TPU-native: the reference's "analysis + IR passes + engine" stack IS
XLA — graph capture is jax tracing, fusion/memory planning is the XLA
pipeline, the engine is a compiled executable. What remains to build
(and is built here) are the parts XLA does NOT own:

  * precision passes — enable_low_precision_inference casts served
    weights + compute to bf16/fp16 (the reference's mixed-precision
    pass); enable_int8_weight_only quantizes weights to int8 with
    per-channel scales and dequantizes at the matmul edge (the PTQ
    weight-only path; halves HBM for the weights)
  * shape bucketing — enable_shape_bucketing pads the batch dim to a
    fixed bucket ladder so arbitrary request sizes hit a BOUNDED set
    of XLA executables (the serving analog of TensorRT's optimization
    profiles)
  * zero-copy IO — handles adopt existing device arrays without a
    host round trip (share_external_data)
  * async execution — run_async returns immediately (XLA dispatch is
    async); the future's .get() materializes
  * warmup + execution stats — precompile the bucket ladder, count
    compiles/hits/latency (the reference's profile summary)
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


class Config:
    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self.model_path = model_path
        self.params_path = params_path
        self._layer = None
        self._donate = True
        self._precision = None          # None | bf16/fp16 jnp dtype
        self._int8_weights = False
        self._buckets: Optional[List[int]] = None
        self._decode: Optional[dict] = None

    # ---- reference-config surface (XLA-internal knobs are no-ops) ----
    def enable_use_gpu(self, *a, **k):
        pass

    def enable_tpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError("TensorRT has no TPU analog; XLA "
                                  "compiles the graph directly")

    # ---- real serving passes ----------------------------------------
    def enable_low_precision_inference(self, dtype="bfloat16"):
        """Mixed-precision pass: serve weights + compute in bf16/fp16
        (reference convert_to_mixed_precision / the gpu fp16 pass)."""
        from paddle_tpu.core import dtype as dtype_mod
        self._precision = dtype_mod.convert_dtype(dtype)
        return self

    def enable_int8_weight_only(self, flag=True):
        """PTQ weight-only int8: per-output-channel symmetric scales.
        The served weights are quantize-dequantized in place (exact
        accuracy parity with an int8 deployment) and the int8 payload
        + scales are kept on each parameter (`_int8_payload`) for an
        int8-native export — HBM savings come from shipping that
        payload, not from this in-memory emulation."""
        self._int8_weights = bool(flag)
        return self

    def enable_decode(self, max_length: int, prefill_buckets=None,
                      temperature=0.0, top_p=None, eos_token_id=None):
        """Serving decode config: fixed-capacity KV cache of
        `max_length`, prefill compiled per bucket, one compiled decode
        step (see inference/decode.py). Enables Predictor.generate."""
        self._decode = dict(max_length=int(max_length),
                            prefill_buckets=prefill_buckets,
                            temperature=temperature, top_p=top_p,
                            eos_token_id=eos_token_id)
        return self

    def enable_shape_bucketing(self, buckets: Sequence[int]):
        """Pad the leading (batch) dim up to the nearest bucket so any
        request size compiles at most len(buckets) executables."""
        self._buckets = sorted(int(b) for b in buckets)
        return self

    def set_model(self, model_path, params_path=None):
        self.model_path = model_path
        self.params_path = params_path

    def set_layer(self, layer):
        """Directly serve an in-memory Layer (fast path)."""
        self._layer = layer


def _quantize_int8(arr, channel_axis):
    """Per-channel symmetric int8 quantization; scales from the single
    quantization-module observer (one home for the scale math)."""
    from paddle_tpu.core.tensor import Tensor as _T
    from paddle_tpu.quantization import GroupWiseWeightObserver
    a = np.asarray(arr, np.float32)
    obs = GroupWiseWeightObserver(channel_axis=channel_axis)
    obs.observe(_T(a))
    ax = channel_axis % a.ndim
    shape = [1] * a.ndim
    shape[ax] = -1
    scale = np.maximum(np.asarray(obs.scale(), np.float32),
                       1e-8).reshape(shape)
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    return q, scale


class Predictor:
    def __init__(self, config: Config):
        self._config = config
        self._layer = config._layer
        if self._layer is None and config.model_path:
            # serve a jit.save artifact: <path>.pdmodel is a serialized
            # jax.export program, loaded as a TranslatedLayer
            import os
            path = config.model_path
            for suffix in (".pdmodel", ".json"):
                if path.endswith(suffix):
                    path = path[:-len(suffix)]
            if os.path.exists(path + ".pdmodel"):
                self._layer = paddle.jit.load(path)
        if self._layer is None:
            raise NotImplementedError(
                "the predictor needs a model: pass Config(model_path) "
                "pointing at a paddle_tpu.jit.save artifact, or use "
                "Config.set_layer(layer) (+ layer.set_state_dict("
                "paddle.load(...)) for file-based weights)")
        self._apply_passes()
        self._inputs: Dict[str, Tensor] = {}
        self._compiled = None
        self._last_out: Optional[Tensor] = None
        self.stats = {"runs": 0, "bucket_pad_total": 0,
                      "last_latency_ms": None, "warmup_shapes": []}

    # ---- precision / quantization passes over the served weights ----
    def _apply_passes(self):
        from paddle_tpu.jit import TranslatedLayer
        cfg = self._config
        if isinstance(self._layer, TranslatedLayer):
            return                      # weights frozen in the program
        if cfg._int8_weights:
            self._int8_rewrite()
        if cfg._precision is not None:
            # composes with int8: QDQ'd weights are then SERVED in the
            # low precision (int8-emulated values, bf16 compute)
            for _, p in self._layer.named_parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._assign_array(p._data.astype(cfg._precision))
            for _, b in self._layer.named_buffers():
                if jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._assign_array(b._data.astype(cfg._precision))

    def _int8_rewrite(self):
        """Quantize-dequantize every >=2-D float parameter in place
        (int8 deployment numerics) and stash the (int8, scale) payload
        on the parameter for int8-native export. Channel convention:
        last dim for matrices (Linear [in, out]), dim 0 for conv
        weights ([out, in, k...])."""
        for _, p in self._layer.named_parameters():
            a = p._data
            if a.ndim >= 2 and jnp.issubdtype(a.dtype, jnp.floating):
                ax = -1 if a.ndim == 2 else 0
                q, scale = _quantize_int8(a, ax)
                deq = jnp.asarray(q, jnp.int8)
                sc = jnp.asarray(scale)
                p._assign_array((deq.astype(jnp.float32) * sc
                                 ).astype(a.dtype))
                p._int8_payload = (deq, sc)   # int8-native export

    # ---- IO handles --------------------------------------------------
    def get_input_names(self):
        return list(self._inputs) or ["x"]

    def get_input_handle(self, name):
        t = self._inputs.setdefault(name, paddle.zeros([1]))
        return _Handle(t)

    def get_output_names(self):
        return ["out"]

    def get_output_handle(self, name):
        # late-binding: reads the output of the most recent run()
        return _OutputHandle(self)

    # ---- execution ---------------------------------------------------
    def _bucketize(self, args):
        """Pad the BATCH dim (the first input's leading dim) up to the
        bucket ladder. Only inputs sharing that batch size are padded —
        side inputs (lookup tables, per-position tensors) pass through
        untouched; outputs whose leading dim is the padded batch are
        trimmed back. Returns (args, true_batch, padded_batch);
        (args, None, None) means no padding happened (None, not 0 —
        a true batch of 0 pads and must still trim)."""
        buckets = self._config._buckets
        if not buckets or not args or args[0]._data.ndim == 0:
            return args, None, None
        batch = args[0].shape[0]
        tgt = next((k for k in buckets if k >= batch), buckets[-1])
        if tgt <= batch:
            return args, None, None
        out = []
        for a in args:
            if a.shape[0] == batch:
                pad = [(0, tgt - batch)] + [(0, 0)] * (a._data.ndim - 1)
                out.append(Tensor._wrap(jnp.pad(a._data, pad), True))
            else:
                out.append(a)
        return out, batch, tgt

    def _batch_output_flags(self, args):
        """Per-output batch relationship, probed with jax.eval_shape at
        two batch sizes (no execution, no compile):
          True  — dim0 IS the batch (safe to pad + trim)
          False — dim0 is batch-independent (pass through)
          "affine" — dim0 depends on the batch but is not equal to it
                     (e.g. 2*B): padding cannot be undone by trimming,
                     so bucketing must be skipped entirely
        None when the model cannot be abstractly evaluated."""
        # normalize the batch dim out of the key: flags depend only on
        # WHICH dims track the batch, so arbitrary request sizes reuse
        # one cache entry instead of re-probing per novel batch size
        batch0 = args[0].shape[0] if args and args[0]._data.ndim else None
        key = tuple(
            (("B",) + tuple(a._data.shape[1:])
             if a._data.ndim and a.shape[0] == batch0
             else tuple(a._data.shape), a._data.dtype.name)
            for a in args)
        if key in getattr(self, "_flag_cache", {}):
            return self._flag_cache[key]
        if not hasattr(self, "_flag_cache"):
            self._flag_cache = {}
        batch = args[0].shape[0]

        def shapes_at(b):
            specs = []
            for a in args:
                shp = list(a._data.shape)
                if shp and shp[0] == batch:
                    shp[0] = b
                specs.append(jax.ShapeDtypeStruct(tuple(shp),
                                                  a._data.dtype))

            def fn(*xs):
                with paddle.no_grad():
                    o = self._layer(*[Tensor._wrap(x, True)
                                      for x in xs])
                o = [o] if isinstance(o, Tensor) else list(o)
                return [t._data for t in o]
            return jax.eval_shape(fn, *specs)

        try:
            b1, b2 = max(batch, 1), max(batch, 1) + 1
            s1 = shapes_at(b1)
            s2 = shapes_at(b2)
            flags = []
            for a, b in zip(s1, s2):
                d1 = a.shape[0] if a.shape else None
                d2 = b.shape[0] if b.shape else None
                if d1 == d2:
                    flags.append(False)
                elif (d1, d2) == (b1, b2):
                    flags.append(True)
                else:
                    flags.append("affine")
        except Exception:
            flags = None                # fall back to the heuristic
        self._flag_cache[key] = flags
        return flags

    def _ensure_compiled(self):
        if self._compiled is None:
            from paddle_tpu.jit import TranslatedLayer
            self._layer.eval()
            if isinstance(self._layer, TranslatedLayer):
                self._compiled = self._layer
            else:
                self._compiled = paddle.jit.to_static(
                    lambda *xs: self._layer(*xs), objs=[self._layer],
                    donate=False)

    def warmup(self, shapes: Sequence[Sequence[int]],
               dtype="float32"):
        """Precompile the executable ladder for the given input shapes
        (serving cold-start elimination; with bucketing, pass one shape
        per bucket)."""
        from paddle_tpu.core import dtype as dtype_mod
        d = dtype_mod.convert_dtype(dtype)
        for shape in shapes:
            x = Tensor._wrap(jnp.zeros(tuple(shape), d), True)
            self.run([x])
            self.stats["warmup_shapes"].append(tuple(shape))
        return self

    def run(self, inputs: Optional[List[Tensor]] = None):
        outs = self._run_impl(inputs, block=True)
        self._last_out = outs[0]
        return outs

    def _run_impl(self, inputs, block, record=True):
        args = inputs if inputs is not None else \
            list(self._inputs.values())
        args = [a if isinstance(a, Tensor) else paddle.to_tensor(a)
                for a in args]
        from paddle_tpu.jit import TranslatedLayer
        if self._config._precision is not None and not isinstance(
                self._layer, TranslatedLayer):
            # TranslatedLayer programs have frozen f32 avals — the
            # precision pass does not apply to them
            args = [Tensor._wrap(a._data.astype(self._config._precision),
                                 True)
                    if jnp.issubdtype(a._data.dtype, jnp.floating)
                    else a for a in args]
        buckets = self._config._buckets
        flags = self._batch_output_flags(args) if buckets and args \
            else None
        # any batch-dependent-but-not-batch output (dim0 = 2B etc.)
        # cannot be padded-and-trimmed NOR chunked: run unbucketed.
        # A failed probe (flags None) also skips bucketing: without
        # per-output knowledge, trimming would have to guess which
        # outputs track the batch.
        bucketable = (not buckets or not args) if flags is None else \
            not any(f == "affine" for f in flags)
        if buckets and args and bucketable \
                and args[0].shape[0] > buckets[-1]:
            # bigger than the top bucket: chunk into top-bucket pieces
            # so the executable count stays bounded by the ladder.
            # Valid only when every output carries the batch — an
            # aggregate output cannot be reassembled from chunks.
            if flags is not None and all(f is True for f in flags):
                top = buckets[-1]
                batch = args[0].shape[0]
                t0 = time.perf_counter()
                pieces = []
                for lo in range(0, batch, top):
                    part = [Tensor._wrap(a._data[lo:lo + top], True)
                            if a.shape[0] == batch else a for a in args]
                    # dispatch chunks WITHOUT a per-chunk barrier so
                    # device work pipelines across them; inner calls
                    # don't touch stats — this is ONE user-visible run
                    pieces.append(self._run_impl(part, block=False,
                                                 record=False))
                outs = [Tensor._wrap(
                    jnp.concatenate([p[i]._data for p in pieces], 0),
                    True) for i in range(len(pieces[0]))]
                if block:
                    jax.block_until_ready([o._data for o in outs])
                if record:
                    self.stats["runs"] += 1
                    self.stats["last_latency_ms"] = \
                        (time.perf_counter() - t0) * 1e3
                return outs
        if bucketable:
            args, true_batch, padded = self._bucketize(args)
        else:
            true_batch = padded = None
        self._ensure_compiled()
        t0 = time.perf_counter()
        with paddle.no_grad():
            out = self._compiled(*args)
        outs = [out] if isinstance(out, Tensor) else list(out)
        if true_batch is not None:
            # trim ONLY the outputs whose leading dim actually tracks
            # the batch (probed abstractly — a [C] aggregate that
            # happens to equal the padded size must NOT be cut)
            outs = [Tensor._wrap(o._data[:true_batch], True)
                    if (flags[i] is True
                        if flags is not None and i < len(flags)
                        else o._data.ndim >= 1 and o.shape[0] == padded)
                    else o
                    for i, o in enumerate(outs)]
            self.stats["bucket_pad_total"] += 1
        if block:
            # latency means device completion, not async dispatch (on
            # the tunneled backend block_until_ready can ack early;
            # this is still the closest generic barrier)
            jax.block_until_ready([o._data for o in outs])
        if record:
            self.stats["runs"] += 1
            self.stats["last_latency_ms"] = \
                (time.perf_counter() - t0) * 1e3
        return outs

    def generate(self, input_ids, max_new_tokens=16, seed=0):
        """Serving generation over the fixed-capacity KV cache: needs
        Config.enable_decode and a layer implementing the
        init_cache/forward_with_cache contract (models/llama.py,
        models/gpt.py). ONE decode executable for all tokens."""
        if self._config._decode is None:
            raise RuntimeError("call Config.enable_decode(max_length) "
                               "before Predictor.generate")
        if not hasattr(self._layer, "forward_with_cache"):
            raise TypeError(
                "the served layer does not expose the decode contract "
                "(init_cache + forward_with_cache)")
        if getattr(self, "_decode_session", None) is None:
            from .decode import DecodeSession
            self._decode_session = DecodeSession(self._layer,
                                                 **self._config._decode)
        t0 = time.perf_counter()
        out = self._decode_session.generate(input_ids, max_new_tokens,
                                            seed=seed)
        self.stats["runs"] += 1
        self.stats["last_latency_ms"] = (time.perf_counter() - t0) * 1e3
        return out

    def run_async(self, inputs: Optional[List[Tensor]] = None):
        """Dispatch without blocking (XLA execution is async by
        design); the returned future materializes on .get()."""
        outs = self._run_impl(inputs, block=False)
        self._last_out = outs[0]
        return _Future(outs)

    def get_execution_stats(self):
        entry = self._compiled
        n_spec = 0
        if entry is not None and hasattr(entry, "specializations"):
            n_spec = sum(len(v) for v in
                         entry.specializations().values())
        return dict(self.stats, executables=n_spec)


class _Future:
    def __init__(self, outs):
        self._outs = outs

    def done(self):
        return True                     # dispatch already queued

    def get(self):
        for o in self._outs:
            jax.block_until_ready(o._data)
        return self._outs


class _OutputHandle:
    """Handle bound to a predictor's latest output (valid after run())."""

    def __init__(self, predictor: "Predictor"):
        self._p = predictor

    def copy_to_cpu(self):
        if self._p._last_out is None:
            raise RuntimeError("no output yet: call Predictor.run() first")
        return self._p._last_out.numpy()

    def shape(self):
        if self._p._last_out is None:
            raise RuntimeError("no output yet: call Predictor.run() first")
        return self._p._last_out.shape


class _Handle:
    """Zero-copy tensor handle parity (reference ZeroCopyTensor)."""

    def __init__(self, t: Tensor):
        self._t = t

    def reshape(self, shape):
        self._t._assign_array(jnp.zeros(shape, self._t._data.dtype))

    def copy_from_cpu(self, arr):
        self._t._assign_array(jnp.asarray(np.asarray(arr)))

    def share_external_data(self, arr):
        """Adopt an existing device array WITHOUT a host round trip
        (reference share_external_data zero-copy path)."""
        if isinstance(arr, Tensor):
            self._t._assign_array(arr._data)
        elif isinstance(arr, jax.Array):
            self._t._assign_array(arr)
        else:
            self._t._assign_array(jnp.asarray(arr))

    def copy_to_cpu(self):
        return self._t.numpy()

    def shape(self):
        return self._t.shape


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# ---------------------------------------------------------------------
# Int8-native serving (consumes the _int8_payload the PTQ pass records)
# ---------------------------------------------------------------------

class Int8Linear(paddle.nn.Layer):
    """Weight-only-int8 serving Linear: HBM holds the int8 payload +
    per-output-channel scales; dequantization happens INSIDE the
    compiled program at the matmul edge, where XLA fuses it into the
    GEMM read (the int8->bf16 convert rides the HBM->MXU path). This is
    the deployable form of the PTQ weight-only pass — reference:
    the int8 weight-only path of analysis_predictor's quant passes."""

    def __init__(self, weight_q, weight_scale, bias=None,
                 compute_dtype="float32"):
        super().__init__()
        from paddle_tpu.core import dtype as dtype_mod
        self._compute_dtype = dtype_mod.convert_dtype(compute_dtype)
        wq = weight_q if isinstance(weight_q, Tensor) else \
            Tensor(np.asarray(weight_q, np.int8))
        sc = weight_scale if isinstance(weight_scale, Tensor) else \
            Tensor(np.asarray(weight_scale, np.float32))
        self.register_buffer("weight_q", wq)
        self.register_buffer("weight_scale", sc)
        self.bias = None
        if bias is not None:
            self.bias = bias if isinstance(bias, Tensor) else Tensor(bias)

    def forward(self, x):
        from paddle_tpu.core.dispatch import run_op

        def f(a, wq, sc, *rest):
            w = wq.astype(self._compute_dtype) * sc.reshape(1, -1)
            out = a.astype(self._compute_dtype) @ w
            if rest:
                out = out + rest[0]
            return out
        args = [x, self.weight_q, self.weight_scale]
        if self.bias is not None:
            args.append(self.bias)
        return run_op("int8_linear", f, *args, differentiable=False)


def apply_int8_rewrite(layer, compute_dtype="float32"):
    """Swap every Linear carrying an _int8_payload for an Int8Linear
    holding the int8 buffer natively. Returns the count swapped."""
    from paddle_tpu.nn.layer.common import Linear as _Linear
    n = 0
    for name, sub in list(layer._sub_layers.items()):
        if isinstance(sub, _Linear) and \
                getattr(sub.weight, "_int8_payload", None) is not None:
            q, scale = sub.weight._int8_payload
            layer._sub_layers[name] = Int8Linear(
                Tensor(np.asarray(q, np.int8)),
                Tensor(np.asarray(scale, np.float32).reshape(-1)),
                bias=sub.bias, compute_dtype=compute_dtype)
            n += 1
        else:
            n += apply_int8_rewrite(sub, compute_dtype)
    return n


def save_int8_model(predictor: Predictor, path: str):
    """Write the int8-native serving artifact: one npz holding each
    quantized Linear's (int8 payload, scales) plus every other state
    tensor in fp. Load with `load_int8_model(layer, path)`."""
    layer = predictor._layer
    if not predictor._config._int8_weights:
        raise ValueError("enable_int8_weight_only() first: the int8 "
                         "payload is recorded by that pass")
    entries = {}
    for name, p in layer.named_parameters():
        payload = getattr(p, "_int8_payload", None)
        if payload is not None:
            q, scale = payload
            entries[name + ".int8"] = np.asarray(q, np.int8)
            entries[name + ".scale"] = np.asarray(scale,
                                                  np.float32).reshape(-1)
        else:
            entries[name] = np.asarray(p._data)
    for name, b in layer.named_buffers():
        entries["buffer:" + name] = np.asarray(b._data)
    np.savez(path, **entries)


def load_int8_model(layer, path: str, compute_dtype="float32"):
    """Restore an int8 serving artifact into a freshly-built layer:
    quantized Linears are swapped to Int8Linear (int8 stays int8 in
    HBM), everything else is loaded as saved."""
    data = np.load(path if path.endswith(".npz") else path + ".npz")
    int8_weights = {k[:-len(".int8")]: data[k] for k in data.files
                    if k.endswith(".int8")}
    scales = {k[:-len(".scale")]: data[k] for k in data.files
              if k.endswith(".scale")}
    for name, p in layer.named_parameters():
        if name in int8_weights:
            # restore QDQ numerics for every quantized param; Linear
            # weights are then swapped to int8-native storage below
            # (non-Linear quantized params, e.g. embeddings, serve the
            # dequantized values — same numerics, fp storage)
            q, sc = int8_weights[name], scales[name]
            ax = -1 if q.ndim == 2 else 0
            shape = [1] * q.ndim
            shape[ax % q.ndim] = -1
            deq = q.astype(np.float32) * sc.reshape(shape)
            p._assign_array(jnp.asarray(deq, p._data.dtype))
            p._int8_payload = (q, sc)
        elif name in data.files:
            p._assign_array(jnp.asarray(data[name]))
    for name, b in layer.named_buffers():
        key = "buffer:" + name
        if key in data.files:
            b._assign_array(jnp.asarray(data[key]))
    apply_int8_rewrite(layer, compute_dtype)
    return layer


def __getattr__(name):
    # serving sessions live in .decode; export them lazily so importing
    # paddle_tpu.inference stays light (the decode module pulls model
    # machinery). The robustness vocabulary (request states, admission
    # exceptions) lives in .admission — stdlib-light, but exported the
    # same way for one import surface.
    if name in ("DecodeSession", "ContinuousBatchingSession"):
        from . import decode
        return getattr(decode, name)
    if name in ("RequestState", "RequestResult", "AdmissionRejected",
                "ServingStepError", "AdmissionController"):
        from . import admission
        return getattr(admission, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
