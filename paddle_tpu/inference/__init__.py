"""paddle.inference equivalent (reference: AnalysisPredictor,
fluid/inference/api/analysis_predictor.h:105 — config + predictor with
zero-copy tensors, pass pipelines, TensorRT bridges).

TPU-native: the "analysis + optimization passes + engine" stack IS XLA;
Predictor wraps a jit-compiled forward with an executable cache. Model
artifacts are paddle_tpu.jit.save outputs (state dict + StableHLO text).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


class Config:
    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self.model_path = model_path
        self.params_path = params_path
        self._layer = None
        self._donate = True

    # reference-config surface (most knobs are XLA-internal now)
    def enable_use_gpu(self, *a, **k):
        pass

    def enable_tpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError("TensorRT has no TPU analog; XLA "
                                  "compiles the graph directly")

    def set_model(self, model_path, params_path=None):
        self.model_path = model_path
        self.params_path = params_path

    def set_layer(self, layer):
        """Directly serve an in-memory Layer (fast path)."""
        self._layer = layer


class Predictor:
    def __init__(self, config: Config):
        self._config = config
        self._layer = config._layer
        if self._layer is None and config.model_path:
            # serve a jit.save artifact: <path>.pdmodel is a serialized
            # jax.export program, loaded as a TranslatedLayer
            import os
            path = config.model_path
            for suffix in (".pdmodel", ".json"):
                if path.endswith(suffix):
                    path = path[:-len(suffix)]
            if os.path.exists(path + ".pdmodel"):
                self._layer = paddle.jit.load(path)
        if self._layer is None:
            raise NotImplementedError(
                "the predictor needs a model: pass Config(model_path) "
                "pointing at a paddle_tpu.jit.save artifact, or use "
                "Config.set_layer(layer) (+ layer.set_state_dict("
                "paddle.load(...)) for file-based weights)")
        self._inputs: Dict[str, Tensor] = {}
        self._compiled = None
        self._last_out: Optional[Tensor] = None

    def get_input_names(self):
        return list(self._inputs) or ["x"]

    def get_input_handle(self, name):
        t = self._inputs.setdefault(name, paddle.zeros([1]))
        return _Handle(t)

    def get_output_names(self):
        return ["out"]

    def get_output_handle(self, name):
        # late-binding: the handle reads the output produced by the most
        # recent run(), so it may be fetched before the first run
        return _OutputHandle(self)

    def run(self, inputs: Optional[List[Tensor]] = None):
        args = inputs if inputs is not None else list(self._inputs.values())
        args = [a if isinstance(a, Tensor) else paddle.to_tensor(a)
                for a in args]
        if self._compiled is None:
            from paddle_tpu.jit import TranslatedLayer
            self._layer.eval()
            if isinstance(self._layer, TranslatedLayer):
                self._compiled = self._layer   # already a compiled program
            else:
                self._compiled = paddle.jit.to_static(
                    lambda *xs: self._layer(*xs), objs=[self._layer],
                    donate=False)
        with paddle.no_grad():
            out = self._compiled(*args)
        self._last_out = out if isinstance(out, Tensor) else out[0]
        return [self._last_out] if isinstance(out, Tensor) else list(out)


class _OutputHandle:
    """Handle bound to a predictor's latest output (valid after run())."""

    def __init__(self, predictor: "Predictor"):
        self._p = predictor

    def copy_to_cpu(self):
        if self._p._last_out is None:
            raise RuntimeError("no output yet: call Predictor.run() first")
        return self._p._last_out.numpy()

    def shape(self):
        if self._p._last_out is None:
            raise RuntimeError("no output yet: call Predictor.run() first")
        return self._p._last_out.shape


class _Handle:
    """Zero-copy tensor handle parity."""

    def __init__(self, t: Tensor):
        self._t = t

    def reshape(self, shape):
        import jax.numpy as jnp
        self._t._assign_array(jnp.zeros(shape, self._t._data.dtype))

    def copy_from_cpu(self, arr):
        import jax.numpy as jnp
        self._t._assign_array(jnp.asarray(np.asarray(arr)))

    def copy_to_cpu(self):
        return self._t.numpy()

    def shape(self):
        return self._t.shape


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
