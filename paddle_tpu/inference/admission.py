"""Admission control + request lifecycle for the serving sessions.

Reference capability being matched: fastdeploy-style serving atop
block_multihead_attention pairs continuous batching with request
timeouts and queue limits, and the Orca/vLLM scheduler lineage gives
every request an explicit lifecycle state. This module is the
host-side policy half of that armor; the device half (slot eviction,
step retry, bisection quarantine) lives in ``inference/decode.py``.

Pieces:

  * :class:`RequestState` — the per-request state machine
    ``QUEUED -> PREFILLING -> DECODING -> {DONE, TIMED_OUT, CANCELLED,
    REJECTED, FAILED}``;
  * :class:`AdmissionController` — a bounded-queue policy: under
    overload the session sheds load with FAST rejections
    (:class:`AdmissionRejected`) instead of letting the queue grow and
    tail latency collapse. Policies: ``reject_newest`` (default) and
    ``priority`` (a higher-priority arrival evicts the newest
    lowest-priority queued request);
  * :class:`RequestResult` — what a drained request resolves to:
    terminal state, full token ids (prompt + whatever was generated
    before the terminal transition), and the error string for FAILED;
  * :class:`ServingStepError` — raised when a persistent device-step
    failure cannot be attributed to a single poison request (whole
    accelerator down); the session's bookkeeping stays consistent so
    the caller can close() or retry.
"""
from __future__ import annotations

import enum
from typing import Deque, Optional

import numpy as np


class RequestState(enum.Enum):
    QUEUED = "QUEUED"
    PREFILLING = "PREFILLING"
    DECODING = "DECODING"
    DONE = "DONE"
    TIMED_OUT = "TIMED_OUT"
    CANCELLED = "CANCELLED"
    REJECTED = "REJECTED"
    FAILED = "FAILED"

    @property
    def terminal(self) -> bool:
        return self in _TERMINAL


_TERMINAL = {RequestState.DONE, RequestState.TIMED_OUT,
             RequestState.CANCELLED, RequestState.REJECTED,
             RequestState.FAILED}


class AdmissionRejected(RuntimeError):
    """Fast rejection: the bounded queue is full and the shedding
    policy chose not to admit this request. Load balancers map this to
    429/503 and route away — the request never waits."""


class ServingStepError(RuntimeError):
    """The device step keeps failing and bisection could not isolate a
    single poison request (both probe halves fail — the failure is
    step-wide, not request-borne)."""


class RequestResult:
    """Terminal outcome of one request."""

    __slots__ = ("state", "ids", "error")

    def __init__(self, state: RequestState, ids: np.ndarray,
                 error: Optional[str] = None):
        self.state = state
        self.ids = ids
        self.error = error

    @property
    def ok(self) -> bool:
        return self.state is RequestState.DONE

    def __repr__(self):
        return (f"RequestResult(state={self.state.name}, "
                f"len={len(self.ids)}"
                + (f", error={self.error!r}" if self.error else "")
                + ")")


POLICIES = ("reject_newest", "priority")


class AdmissionController:
    """Bounded-queue shedding policy over the session's deque.

    ``max_queue=None`` disables the bound (legacy behavior — the
    session accepts everything). With a bound, :meth:`admit` either
    admits (possibly evicting a queued victim under the ``priority``
    policy) or raises :class:`AdmissionRejected`.
    """

    def __init__(self, max_queue: Optional[int] = None,
                 policy: str = "reject_newest",
                 degraded_queue_frac: float = 0.8):
        if policy not in POLICIES:
            raise ValueError(
                f"shed policy {policy!r} not in {POLICIES}")
        if max_queue is not None and int(max_queue) < 1:
            raise ValueError("max_queue must be >= 1 (or None)")
        self.max_queue = int(max_queue) if max_queue is not None else None
        self.policy = policy
        #: queue-depth fraction past which readiness reports degraded
        self.degraded_queue_frac = float(degraded_queue_frac)

    def admit(self, queue: Deque, req, free_slots: int = 0
              ) -> Optional[object]:
        """Decide admission for ``req`` against the current queue.

        The bound applies to requests WAITING beyond free slot
        capacity: a request the next step can admit straight into a
        slot is never shed. Returns the evicted victim request
        (priority policy) or None; the CALLER appends ``req`` and
        retires the victim. Raises :class:`AdmissionRejected` when
        the request is shed."""
        if self.max_queue is None or \
                len(queue) - free_slots < self.max_queue:
            return None
        if self.policy == "priority":
            # evict the NEWEST among the strictly-lower-priority queued
            # requests (newest first: it has waited least, so shedding
            # it wastes the least sunk queue time)
            victim_i = None
            for i in range(len(queue) - 1, -1, -1):
                if queue[i].priority < req.priority:
                    victim_i = i
                    break
            if victim_i is not None:
                victim = queue[victim_i]
                del queue[victim_i]
                return victim
        raise AdmissionRejected(
            f"queue full ({self.max_queue}): request shed by "
            f"{self.policy} policy")

    def degraded_reasons(self, queue_len: int, free_slots: int) -> list:
        """Readiness probe: non-empty list of reasons when the session
        should report degraded (503 on /healthz) so load balancers
        route away before the shedding policy has to fire."""
        reasons = []
        if (self.max_queue is not None
                and queue_len - free_slots
                >= self.degraded_queue_frac * self.max_queue):
            reasons.append(
                f"queue_pressure:{queue_len - free_slots}"
                f"/{self.max_queue}")
        if free_slots == 0 and queue_len > 0:
            reasons.append(f"slot_pressure:backlog={queue_len}")
        return reasons
