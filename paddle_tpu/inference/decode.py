"""TPU-native decode/serving path: static KV cache + one compiled step.

Reference being reproduced:
  * masked_multihead_attention decode kernel
    (/root/reference/python/paddle/incubate/nn/functional/masked_multihead_attention.py)
  * block_multihead_attention paged-KV serving attention
    (/root/reference/python/paddle/incubate/nn/functional/block_multihead_attention.py)
  * the serving role of AnalysisPredictor
    (/root/reference/paddle/fluid/inference/api/analysis_predictor.h:105)

TPU-native design. GPU serving pages the KV cache because CUDA kernels can
chase block tables; on TPU every program is compiled with static shapes, so
the idiomatic equivalent is a FIXED-CAPACITY dense cache ``[B, C, Hkv, D]``
plus a per-sequence length counter:

  * the cache is updated in place with ``lax.dynamic_update_slice`` — XLA
    aliases the donated buffer, so this is a true in-place write in HBM;
  * attention masks columns ``>= length``, so capacity padding never leaks;
  * ONE jitted decode step (embed -> attention against the cache prefix ->
    sample) is reused for every generated token — zero recompiles after
    warmup;
  * prefill runs as a second static program per bucketed prompt length.

`DecodeSession` packages this: it traces the model's cached forward into
pure jax functions (weights passed as inputs, cache donated), and exposes
``generate``.
"""
from __future__ import annotations

import collections
import math
import time
import weakref
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.dispatch import run_op
from paddle_tpu.observability import metrics as _met
from paddle_tpu.observability import server as _obs_server
from paddle_tpu import _chaos
from paddle_tpu.inference import admission as _adm
from paddle_tpu.inference.admission import (AdmissionRejected,  # noqa: F401
                                            RequestResult, RequestState,
                                            ServingStepError)

# Per-layer fixed-capacity cache. k/v: [B, C, num_kv_heads, head_dim];
# length: [B] int32 — number of valid positions per sequence.
StaticCache = collections.namedtuple("StaticCache", ["k", "v", "length"])


def init_static_cache(batch_size, capacity, num_kv_heads, head_dim,
                      dtype="float32"):
    """Allocate one layer's fixed-capacity KV cache."""
    _chaos.hit("serving.cache_alloc", batch=batch_size,
               capacity=capacity)
    from paddle_tpu.ops.creation import zeros
    k = zeros([batch_size, capacity, num_kv_heads, head_dim], dtype=dtype)
    v = zeros([batch_size, capacity, num_kv_heads, head_dim], dtype=dtype)
    length = zeros([batch_size], dtype="int32")
    return StaticCache(k, v, length)


def _write_kv(buf, new, lens):
    """Write new [B, s, H, D] into buf [B, C, H, D] at per-seq offsets."""
    return jax.vmap(
        lambda b, n, l: lax.dynamic_update_slice(b, n, (l, 0, 0))
    )(buf, new, lens)


def _cache_attention(q, kn, vn, kbuf, vbuf, lens):
    """Write-then-attend against a fixed-capacity cache.

    q: [B, s, H, D] new queries; kn/vn: [B, s, Hkv, D] new keys/values;
    kbuf/vbuf: [B, C, Hkv, D]; lens: [B] valid lengths BEFORE this call.
    Returns (out [B, s, H, D], kbuf', vbuf', lens + s). GQA is handled by
    grouping the query heads — the cache is never materialized at H heads.
    """
    b, s, h, d = q.shape
    c = kbuf.shape[1]
    hkv = kbuf.shape[2]
    kbuf = _write_kv(kbuf, kn.astype(kbuf.dtype), lens)
    vbuf = _write_kv(vbuf, vn.astype(vbuf.dtype), lens)
    g = h // hkv
    qg = q.reshape(b, s, hkv, g, d).astype(jnp.float32)
    scale = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bskgd,bckd->bkgsc", qg,
                        kbuf.astype(jnp.float32)) * scale
    col = jnp.arange(c)[None, None, None, None, :]
    row = jnp.arange(s)[None, None, None, :, None]
    valid = col < (lens[:, None, None, None, None] + row + 1)
    logits = jnp.where(valid, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgsc,bckd->bskgd", probs,
                     vbuf.astype(jnp.float32))
    return (out.reshape(b, s, h, d).astype(q.dtype), kbuf, vbuf,
            lens + jnp.int32(s))


def _check_capacity(length, s_new, capacity):
    """Eager misuse guard: writing past capacity would silently clamp
    (dynamic_update_slice semantics) and corrupt the newest cache slot.
    Lengths are concrete in eager mode — check them; under a trace
    (DecodeSession / user jit) lengths are tracers and this is a no-op,
    so the compiled serving path pays nothing. The eager check costs one
    tiny device sync per step; disable with
    FLAGS_kv_capacity_check=false when an eager loop is latency-bound
    and externally guarded."""
    arr = length._data if isinstance(length, Tensor) else length
    if isinstance(arr, jax.core.Tracer):
        return
    from paddle_tpu.core.flags import get_flag
    if not get_flag("FLAGS_kv_capacity_check"):
        return
    top = int(jax.device_get(jnp.max(arr))) + s_new
    if top > capacity:
        raise ValueError(
            f"KV cache overflow: writing {s_new} token(s) at length "
            f"{top - s_new} exceeds capacity {capacity}")


def cache_attention(q, k_new, v_new, cache: StaticCache):
    """Eager-op wrapper: attend q against (cache ++ new kv), updating the
    cache in place. Returns (out, new_cache). Not differentiable (serving
    path)."""
    _check_capacity(cache.length, q.shape[1], cache.k.shape[1])
    out, k2, v2, l2 = run_op(
        "masked_cache_attention", _cache_attention, q, k_new, v_new,
        cache.k, cache.v, cache.length, n_outputs=4, differentiable=False)
    return out, StaticCache(k2, v2, l2)


def masked_multihead_attention_impl(x, cache_kv, seq_lens, num_heads,
                                    rotary_theta: Optional[float] = None):
    """Reference masked_multihead_attention semantics on the static cache.

    x: [B, 3*H*D] fused qkv for ONE decode step; cache_kv: [2, B, H, C, D]
    (the reference's cache layout); seq_lens: [B] int32 lengths before this
    step. Returns (out [B, H*D], new cache_kv).
    """
    _check_capacity(seq_lens, 1, (cache_kv.shape[3] if hasattr(
        cache_kv, "shape") else cache_kv._data.shape[3]))

    def f(xa, ck, lens):
        b = xa.shape[0]
        h = num_heads
        d = xa.shape[1] // (3 * h)
        qkv = xa.reshape(b, 3, h, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]     # [B, H, D]
        if rotary_theta is not None:
            pos = lens.astype(jnp.float32)            # [B]
            inv = 1.0 / (rotary_theta ** (
                jnp.arange(0, d, 2, dtype=jnp.float32) / d))
            freqs = pos[:, None] * inv[None, :]       # [B, D/2]
            cos = jnp.cos(freqs)[:, None, :]
            sin = jnp.sin(freqs)[:, None, :]

            def rot(a):
                a1, a2 = a[..., 0::2], a[..., 1::2]
                o1 = a1 * cos - a2 * sin
                o2 = a2 * cos + a1 * sin
                return jnp.stack([o1, o2], -1).reshape(a.shape)
            q, k = rot(q), rot(k)
        # cache layout [2, B, H, C, D] -> our [B, C, H, D]
        kbuf = jnp.swapaxes(ck[0], 1, 2)
        vbuf = jnp.swapaxes(ck[1], 1, 2)
        out, kbuf, vbuf, _ = _cache_attention(
            q[:, None], k[:, None], v[:, None], kbuf, vbuf, lens)
        new_ck = jnp.stack([jnp.swapaxes(kbuf, 1, 2),
                            jnp.swapaxes(vbuf, 1, 2)])
        return out.reshape(b, h * d), new_ck
    return run_op("masked_multihead_attention", f, x, cache_kv, seq_lens,
                  n_outputs=2, differentiable=False)


def _sample(logits, key, temperature, top_p, top_k=None):
    """On-device sampling: greedy / temperature / top-k / nucleus."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), key
    if top_k is not None and top_k > 0:
        # clamp so over-large configs degrade to no-op filtering instead
        # of a shape error deep inside the compiled step
        kth = lax.top_k(logits,
                        int(min(top_k, logits.shape[-1])))[0][..., -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    probs = jax.nn.softmax(logits.astype(jnp.float32) / temperature, -1)
    if top_p is not None and top_p < 1.0:
        sorted_p = jnp.sort(probs, axis=-1)[..., ::-1]
        cum = jnp.cumsum(sorted_p, axis=-1)
        # smallest set whose mass exceeds top_p: keep p >= threshold
        k = jnp.sum(cum - sorted_p < top_p, axis=-1, keepdims=True)
        thresh = jnp.take_along_axis(sorted_p, k - 1, axis=-1)
        probs = jnp.where(probs >= thresh, probs, 0.0)
        probs = probs / jnp.sum(probs, -1, keepdims=True)
    key, sub = jax.random.split(key)
    nxt = jax.random.categorical(sub, jnp.log(jnp.maximum(probs, 1e-30)))
    return nxt.astype(jnp.int32), key


def _collect_model_state(model):
    """Dedup'd parameters + buffers (the jit.StaticFunction state
    discipline) — shared by DecodeSession and the continuous-batching
    session."""
    out, seen = [], set()
    for _, p in model.named_parameters():
        if id(p) not in seen:
            seen.add(id(p))
            out.append(p)
    for _, b in model.named_buffers():
        if id(b) not in seen:
            seen.add(id(b))
            out.append(b)
    return out


def _bind_and_run(model, state_tensors, state_arrays, ids_arr,
                  cache_treedef, cache_arrays):
    """Rebind traced state into the live model and run its cached
    forward (the jit.StaticFunction discipline, serving-only)."""
    import paddle_tpu as paddle
    saved = [t._data for t in state_tensors]
    try:
        for t, a in zip(state_tensors, state_arrays):
            t._data = a
        caches = jax.tree_util.tree_unflatten(
            cache_treedef,
            [Tensor._wrap(a, True) for a in cache_arrays])
        caches = [StaticCache(*c) for c in caches]
        with paddle.no_grad():
            logits, caches = model.forward_with_cache(
                Tensor._wrap(ids_arr, True), caches)
        cache_out = [a._data for a in jax.tree_util.tree_leaves(
            [tuple(c) for c in caches],
            is_leaf=lambda x: isinstance(x, Tensor))]
        return logits._data, cache_out
    finally:
        for t, s in zip(state_tensors, saved):
            t._data = s


def _default_buckets(max_length):
    b, out = 16, []
    while b < max_length:
        out.append(b)
        b *= 2
    out.append(max_length)
    return out


class _SessionLifecycle:
    """Shared close()/context-manager/finalizer protocol for serving
    sessions: one refcount on the PADDLE_TPU_METRICS_PORT scrape
    endpoint, taken in __init__ (session_started) and released exactly
    once here — the last session closing shuts the server down and
    frees the port."""

    def close(self):
        """Release session-held resources. Idempotent; also runs via
        the context-manager exit and the finalizer."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        if getattr(self, "_metrics_server", None) is not None:
            self._metrics_server = None
            _obs_server.session_finished()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DecodeSession(_SessionLifecycle):
    """Compiled serving session over a causal-LM Layer.

    The model must implement ``init_cache(batch_size, max_length=C)`` ->
    list[StaticCache] and ``forward_with_cache(ids, caches)`` ->
    (logits, caches); `LlamaForCausalLM` / `GPTForCausalLM` do.

    Two executables total (plus one prefill per prompt bucket): cache
    buffers are donated to the decode step so generation runs at a single
    cache's HBM footprint with zero recompiles after warmup.
    """

    def __init__(self, model, max_length, prefill_buckets=None,
                 temperature=0.0, top_p=None, top_k=None,
                 eos_token_id=None, decode_block=None):
        model.eval()
        self._model = model
        self._max_length = int(max_length)
        self._buckets = sorted(prefill_buckets or
                               _default_buckets(self._max_length))
        self._temperature = float(temperature)
        self._top_p = top_p
        self._top_k = top_k
        self._eos = eos_token_id
        self._buckets = [min(b, self._max_length) for b in self._buckets]
        self._state = self._collect_state()
        # decode_block > 1 selects the SINGLE-PROGRAM multi-token loop:
        # one lax.while_loop program emits a [B, decode_block] token
        # block per dispatch, so decode throughput is independent of
        # host<->device round-trip latency (the per-token dispatch loop
        # serializes on RTT over a tunneled transport). The reference
        # gets the same effect by fusing the whole decode stack into
        # fused_multi_transformer's one-kernel-per-token loop.
        self._decode_block = int(decode_block) if decode_block else None
        # one jitted decode step; cache buffers donated (decode args are
        # (*state, token, key, *cache_leaves) -> caches start at n+2)
        n_state = len(self._state)
        self._decode_jit = jax.jit(
            self._decode_pure,
            donate_argnums=tuple(range(n_state + 2,
                                       n_state + 2 + self._n_cache_leaves)))
        # block program args: (*state, token, key, finished, m,
        # *cache_leaves) -> caches start at n+4
        self._decode_block_jit = jax.jit(
            self._decode_block_pure,
            donate_argnums=tuple(range(n_state + 4,
                                       n_state + 4 + self._n_cache_leaves)))
        self._prefill_jit = jax.jit(self._prefill_pure)
        # pull-based scrape endpoint (PADDLE_TPU_METRICS_PORT): hold
        # one ref for this session's lifetime; close() releases it
        self._metrics_server = _obs_server.session_started()
        self._closed = False

    # -- state plumbing (same discipline as jit.StaticFunction) ---------
    def _collect_state(self):
        return _collect_model_state(self._model)

    @property
    def _n_cache_leaves(self):
        if not hasattr(self, "_cache_leaves_n"):
            c = self._model.init_cache(1, max_length=8)
            self._cache_leaves_n = len(jax.tree_util.tree_leaves(
                [tuple(x._data for x in layer) for layer in c]))
        return self._cache_leaves_n

    def _run_model(self, state_arrays, ids_arr, cache_arrays):
        return _bind_and_run(self._model, self._state, state_arrays,
                             ids_arr, self._cache_treedef, cache_arrays)

    def _prefill_pure(self, *flat):
        n = len(self._state)
        state, (ids, lens, key) = flat[:n], flat[n:n + 3]
        cache_arrays = flat[n + 3:]
        logits, cache_out = self._run_model(state, ids, cache_arrays)
        # last VALID position's logits, per sequence
        b = ids.shape[0]
        last = logits[jnp.arange(b), lens - 1]
        nxt, key = _sample(last, key, self._temperature, self._top_p,
                           self._top_k)
        # prefill wrote the full padded block: reset lengths to the true
        # prompt lengths (padding slots get overwritten by decode steps).
        # The length leaf is located structurally via the cache treedef,
        # not sniffed by dtype.
        layers = jax.tree_util.tree_unflatten(self._cache_treedef,
                                              cache_out)
        layers = [(k, v, lens) for (k, v, _l) in layers]
        cache_out = jax.tree_util.tree_leaves(layers)
        return nxt, key, cache_out

    def _decode_pure(self, *flat):
        n = len(self._state)
        state, token, key = flat[:n], flat[n], flat[n + 1]
        cache_arrays = flat[n + 2:]
        logits, cache_out = self._run_model(state, token[:, None],
                                            cache_arrays)
        nxt, key = _sample(logits[:, -1], key, self._temperature,
                           self._top_p, self._top_k)
        return nxt, key, cache_out

    def _decode_block_pure(self, *flat):
        """Up to ``decode_block`` decode steps in ONE program: a
        lax.while_loop carrying (token, key, finished, out, caches) that
        exits early when every sequence has emitted eos — the early-exit
        check rides ON DEVICE instead of costing a host sync. ``m``
        (actual steps wanted) is a traced operand, so short final blocks
        reuse the same executable."""
        n = len(self._state)
        state = flat[:n]
        token, key, finished, m = flat[n:n + 4]
        cache_arrays = tuple(flat[n + 4:])
        blk = self._decode_block
        eos = self._eos
        fill = jnp.int32(eos if eos is not None else 0)
        out0 = jnp.full((token.shape[0], blk), fill)

        def cond(carry):
            i, _token, _key, fin, _out, _caches = carry
            live = i < m
            if eos is not None:
                live = live & ~jnp.all(fin)
            return live

        def body(carry):
            i, token, key, fin, out, caches = carry
            logits, cache_out = self._run_model(state, token[:, None],
                                                caches)
            nxt, key = _sample(logits[:, -1], key, self._temperature,
                               self._top_p, self._top_k)
            if eos is not None:
                nxt = jnp.where(fin, jnp.int32(eos), nxt)
                fin = fin | (nxt == eos)
            out = out.at[:, i].set(nxt)
            return (i + 1, nxt, key, fin, out, tuple(cache_out))

        carry = (jnp.int32(0), token, key, finished, out0, cache_arrays)
        _i, token, key, finished, out, cache_arrays = lax.while_loop(
            cond, body, carry)
        return out, token, key, finished, list(cache_arrays)

    # -- public API -----------------------------------------------------
    def generate(self, input_ids, max_new_tokens=16, seed=None):
        """Generate tokens; returns [B, prompt + n_generated] ids.

        seed=None (default) draws the sampling key from the framework's
        global generator — successive calls produce different samples,
        matching the legacy eager path; pass an int for reproducibility.
        Sequences that emit eos_token_id are pinned to eos for the rest
        of the batch (per-sequence finished state); the loop exits early
        once every sequence has finished (checked every 8 steps so the
        device pipeline is not serialized by per-token host syncs)."""
        t0 = time.perf_counter()
        ids = input_ids._data if isinstance(input_ids, Tensor) else \
            jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        b, s = ids.shape
        # every generated token except the last is written into the
        # cache, so occupancy reaches s + max_new_tokens - 1
        if s + max_new_tokens - 1 > self._max_length:
            raise ValueError(
                f"prompt ({s}) + {max_new_tokens} new tokens exceeds the "
                f"cache capacity max_length={self._max_length}")
        bucket = next((k for k in self._buckets if k >= s),
                      self._max_length)
        padded = jnp.pad(ids, ((0, 0), (0, bucket - s)))
        lens = jnp.full((b,), s, jnp.int32)
        caches = self._model.init_cache(b, max_length=self._max_length)
        self._cache_treedef = jax.tree_util.tree_structure(
            [tuple(c) for c in caches])
        cache_arrays = [x._data for c in caches for x in c]
        state = [t._data for t in self._state]
        if seed is None:
            from paddle_tpu.core import generator as gen_mod
            key = gen_mod.default_generator().next_key()
        else:
            key = jax.random.PRNGKey(seed)

        token, key, cache_arrays = self._prefill_jit(
            *state, padded, lens, key, *cache_arrays)
        finished = jnp.zeros((b,), bool) if self._eos is not None else None
        if finished is not None:
            finished = finished | (token == self._eos)

        if self._decode_block:
            gen = self._generate_blocks(state, token, key, finished,
                                        cache_arrays, b,
                                        max_new_tokens - 1)
            if _met._ENABLED:
                jax.block_until_ready(gen)
            self._record_generate(t0, b, s, int(gen.shape[1]))
            return Tensor._wrap(jnp.concatenate([ids, gen], axis=1),
                                True)

        outs = [token]
        for i in range(max_new_tokens - 1):
            token, key, cache_arrays = self._decode_jit(
                *state, token, key, *cache_arrays)
            if finished is not None:
                # pin finished sequences to eos; update finished state
                token = jnp.where(finished, jnp.int32(self._eos), token)
                finished = finished | (token == self._eos)
            outs.append(token)
            # early exit probed only every 8 steps: keeps dispatch async
            if finished is not None and (i % 8 == 7) and bool(
                    jax.device_get(jnp.all(finished))):
                break
        gen = jnp.stack(outs, axis=1)
        if _met._ENABLED:
            # close the timing window on completion, not dispatch —
            # async futures would report impossible tokens/s
            jax.block_until_ready(gen)
        self._record_generate(t0, b, s, len(outs))
        return Tensor._wrap(jnp.concatenate([ids, gen], axis=1), True)

    @staticmethod
    def _record_generate(t0, batch, prompt_len, n_new):
        if not _met._ENABLED:
            return
        dt = time.perf_counter() - t0
        r = _met.REGISTRY
        r.counter("serving.generate_calls").inc()
        r.counter("serving.prefill_tokens").inc(batch * prompt_len)
        r.counter("serving.decode_tokens").inc(batch * n_new)
        r.histogram("serving.generate_latency_s").observe(dt)
        if dt > 0:
            r.gauge("serving.decode_tokens_per_s").set(
                batch * n_new / dt)

    def _generate_blocks(self, state, token, key, finished, cache_arrays,
                         b, m_total):
        """Drive the single-program block decoder: one dispatch per
        ``decode_block`` tokens (host RTT amortized by the block size);
        a finished batch stops between blocks and back-fills eos, which
        matches the per-step path's eos pinning token-for-token."""
        blk = self._decode_block
        if finished is None:
            finished = jnp.zeros((b,), bool)
        outs = [token[:, None]]
        done = 0
        while done < m_total:
            m = min(blk, m_total - done)
            toks, token, key, finished, cache_arrays = \
                self._decode_block_jit(*state, token, key, finished,
                                       jnp.int32(m), *cache_arrays)
            outs.append(toks[:, :m])
            done += m
            if self._eos is not None and done < m_total and bool(
                    jax.device_get(jnp.all(finished))):
                outs.append(jnp.full((b, m_total - done),
                                     jnp.int32(self._eos)))
                break
        return jnp.concatenate(outs, axis=1)

    def executable_counts(self):
        """(n_prefill_executables, n_decode_executables) — the decode
        count must stay 1 however many tokens are generated. In block
        mode the block program is THE decode executable (the per-step
        one goes unused), so the counts are summed."""
        return (self._prefill_jit._cache_size(),
                self._decode_jit._cache_size()
                + self._decode_block_jit._cache_size())



class _Request:
    __slots__ = ("rid", "ids", "plen", "budget", "tokens", "slot",
                 "t_submit", "state", "priority", "deadline",
                 "ttft_deadline", "error")

    def __init__(self, rid, ids, plen, budget, priority=0,
                 deadline_s=None, ttft_deadline_s=None):
        self.rid, self.ids, self.plen = rid, ids, plen
        self.budget = budget
        self.tokens: List[int] = []
        self.slot = None
        self.t_submit = time.perf_counter()
        self.state = RequestState.QUEUED
        self.priority = int(priority)
        # deadlines are absolute perf_counter instants; None = no bound
        self.deadline = (self.t_submit + deadline_s
                         if deadline_s is not None else None)
        self.ttft_deadline = (self.t_submit + ttft_deadline_s
                              if ttft_deadline_s is not None else None)
        self.error = None

    def deadline_hit(self, now):
        """Total deadline always applies; the TTFT deadline only until
        the first token has been DELIVERED (drained to the host)."""
        if self.deadline is not None and now > self.deadline:
            return True
        return (self.ttft_deadline is not None and not self.tokens
                and now > self.ttft_deadline)


class ContinuousBatchingSession(_SessionLifecycle):
    """Continuous batching over the dense fixed-capacity cache: requests
    are admitted into free SLOTS and retired mid-flight while decode
    keeps running for the other slots.

    Reference role being re-designed: block_multihead_attention's paged
    KV cache exists to serve variable-length multi-request batches
    (/root/reference/python/paddle/incubate/nn/functional/
    block_multihead_attention.py) with dynamic insertion. On TPU the
    paged indirection is replaced by the static [slots, capacity] cache
    plus per-slot lengths; the dynamic part is slot management:

      * admit  — ONE executable per prompt bucket: slice the slot's
        cache rows out of the batch, run a b=1 prefill on the padded
        prompt, write the rows back at a TRACED slot index and deposit
        the first sampled token into the batched token vector;
      * decode — ONE executable, always the full slot batch; retired /
        empty slots are masked (their length is pinned so the cache
        valid region never moves, and their token is passed through);
      * retire — host-side: eos or budget exhaustion frees the slot,
        the next queued request is admitted into it on the next step.

    Executable count is bounded by 1 + #prefill_buckets regardless of
    how many requests flow through. Sampling uses one device RNG
    stream; with temperature=0 (default) outputs are bit-identical to
    isolated DecodeSession runs (asserted in
    tests/test_continuous_batching.py).
    """

    def __init__(self, model, max_slots, max_length,
                 prefill_buckets=None, temperature=0.0, top_p=None,
                 top_k=None, eos_token_id=None, seed=0,
                 sync_every=1, decode_block=None,
                 max_queue=None, shed_policy="reject_newest",
                 default_deadline_s=None, default_ttft_s=None,
                 step_retries=2, step_backoff_s=0.02,
                 degraded_queue_frac=0.8):
        model.eval()
        self._model = model
        self._slots = int(max_slots)
        self._max_length = int(max_length)
        self._buckets = sorted(
            min(b, self._max_length)
            for b in (prefill_buckets
                      or _default_buckets(self._max_length)))
        self._temperature = float(temperature)
        self._top_p = top_p
        self._top_k = top_k
        self._eos = eos_token_id
        self._state_t = _collect_model_state(model)

        caches = model.init_cache(self._slots,
                                  max_length=self._max_length)
        self._cache_treedef = jax.tree_util.tree_structure(
            [tuple(c) for c in caches])
        self._cache_arrays = [x._data for c in caches for x in c]
        self._tokens = jnp.zeros((self._slots,), jnp.int32)
        self._key = jax.random.PRNGKey(seed)

        n = len(self._state_t)
        nc = len(self._cache_arrays)
        # admit args: (*state, ids, plen, slot, tokens, key, *caches)
        self._admit_jit = jax.jit(
            self._admit_pure,
            donate_argnums=tuple(range(n + 5, n + 5 + nc)))
        # decode args: (*state, tokens, key, active, *caches)
        self._decode_jit = jax.jit(
            self._decode_pure,
            donate_argnums=tuple(range(n + 3, n + 3 + nc)))

        self._free = list(range(self._slots))
        self._queue: collections.deque = collections.deque()
        self._running: dict = {}          # slot -> _Request
        self._done: dict = {}             # rid -> _Request (undelivered)
        self._next_rid = 0
        self._used_rids: set = set()
        # sync_every=k batches the host-side retirement check: token
        # vectors stay ON DEVICE for k decode steps and are fetched in
        # one device_get — over a high-RTT transport the per-token sync
        # dominates (measured 59 vs 150 tok/s on the tunneled chip), so
        # serving callers want k ~ 8. Retirement then lags up to k-1
        # steps (the freed slot's extra decodes are discarded; its
        # cache is reset by the next admission), trading a little
        # wasted compute for dispatch pipelining — the same trade the
        # reference's block-scheduler makes with its step quantum.
        self._sync_every = max(1, int(sync_every))
        self._pending: List = []
        self._t_last_drain = None
        # decode_block=k runs k decode steps per DISPATCH in one
        # lax.while_loop program (the DecodeSession block-decode idea
        # applied to the slot batch): one dispatch emits a [slots, k]
        # token block, amortizing the per-step dispatch cost.
        # sync_every counts DISPATCHES in either mode, so block mode
        # drains every sync_every blocks (retirement lag up to
        # k*sync_every - 1 steps, same discard semantics); the usual
        # block config is sync_every=1 + decode_block=k.
        self._decode_block = int(decode_block) if decode_block else None
        if self._decode_block:
            self._decode_blk_jit = jax.jit(
                self._decode_block_pure,
                donate_argnums=tuple(range(n + 3, n + 3 + nc)))
        # robustness knobs (ISSUE 14): bounded-queue admission control
        # with a pluggable shedding policy, per-request deadline
        # defaults, and the device-step retry envelope
        self._admission = _adm.AdmissionController(
            max_queue=max_queue, policy=shed_policy,
            degraded_queue_frac=degraded_queue_frac)
        self._default_deadline_s = default_deadline_s
        self._default_ttft_s = default_ttft_s
        self._step_retries = max(0, int(step_retries))
        self._step_backoff_s = float(step_backoff_s)
        # readiness: /healthz flips to 503 `degraded` while this
        # session reports queue/slot pressure, so load balancers route
        # away BEFORE the shedding policy has to reject. Registered
        # through a weakref so the module-global provider list never
        # pins an abandoned session alive (close()'s finalizer path
        # must stay reachable).
        wself = weakref.ref(self)

        def _provider():
            s = wself()
            return s._health_report() if s is not None else None
        self._health_unreg = _obs_server.register_health_provider(
            _provider)
        # pull-based scrape endpoint (PADDLE_TPU_METRICS_PORT): hold
        # one ref for this session's lifetime; close() releases it
        self._metrics_server = _obs_server.session_started()
        self._closed = False

    # ---------------- compiled programs ------------------------------
    def _slot_slice(self, cache_arrays, slot):
        layers = jax.tree_util.tree_unflatten(self._cache_treedef,
                                              cache_arrays)
        sliced = [tuple(lax.dynamic_slice_in_dim(a, slot, 1, 0)
                        for a in layer) for layer in layers]
        # fresh slot: the valid region restarts at 0
        sliced = [(k, v, jnp.zeros_like(ln))
                  for (k, v, ln) in sliced]
        return jax.tree_util.tree_leaves(sliced)

    def _slot_unslice(self, cache_arrays, slot_leaves, slot, plen):
        full = jax.tree_util.tree_unflatten(self._cache_treedef,
                                            cache_arrays)
        part = jax.tree_util.tree_unflatten(self._cache_treedef,
                                            slot_leaves)
        out = []
        for (fk, fv, fl), (pk, pv, _pl) in zip(full, part):
            out.append((
                lax.dynamic_update_slice_in_dim(fk, pk, slot, 0),
                lax.dynamic_update_slice_in_dim(fv, pv, slot, 0),
                lax.dynamic_update_index_in_dim(fl, plen, slot, 0)))
        return jax.tree_util.tree_leaves(out)

    def _admit_pure(self, *flat):
        n = len(self._state_t)
        state = flat[:n]
        ids, plen, slot, tokens, key = flat[n:n + 5]
        cache_arrays = flat[n + 5:]
        slot_leaves = self._slot_slice(cache_arrays, slot)
        logits, slot_out = _bind_and_run(
            self._model, self._state_t, state, ids,
            self._cache_treedef, slot_leaves)
        last = logits[0, plen - 1]
        nxt, key = _sample(last[None], key, self._temperature,
                           self._top_p, self._top_k)
        tokens = lax.dynamic_update_index_in_dim(tokens, nxt[0],
                                                 slot, 0)
        cache_arrays = self._slot_unslice(cache_arrays, slot_out,
                                          slot, plen)
        return tokens, key, cache_arrays

    def _masked_step(self, state, tok, key, active, cache_arrays):
        """ONE masked decode step — the single home of the per-slot
        semantics shared by the per-step and block programs: inactive
        slots pass their token through and keep their cache length
        pinned (their valid region must not move while they wait for
        the next admission; the k/v rows the masked step wrote there
        are dead — the next admit's prefill overwrites the slot from
        position 0)."""
        logits, cache_out = _bind_and_run(
            self._model, self._state_t, state, tok[:, None],
            self._cache_treedef, list(cache_arrays))
        nxt, key = _sample(logits[:, -1], key, self._temperature,
                           self._top_p, self._top_k)
        nxt = jnp.where(active, nxt, tok)
        old = jax.tree_util.tree_unflatten(self._cache_treedef,
                                           list(cache_arrays))
        new = jax.tree_util.tree_unflatten(self._cache_treedef,
                                           cache_out)
        fixed = [(k, v, jnp.where(active, ln, lo))
                 for (k, v, ln), (_k, _v, lo) in zip(new, old)]
        return nxt, key, jax.tree_util.tree_leaves(fixed)

    def _decode_block_pure(self, *flat):
        """`decode_block` batched decode steps in ONE program: a
        while_loop over _masked_step carrying (tokens, key, out,
        caches)."""
        n = len(self._state_t)
        state = flat[:n]
        tokens, key, active = flat[n:n + 3]
        cache_arrays = tuple(flat[n + 3:])
        blk = self._decode_block
        out0 = jnp.zeros((self._slots, blk), jnp.int32)

        def body(carry):
            i, tok, key, out, caches = carry
            nxt, key, fixed = self._masked_step(state, tok, key,
                                                active, caches)
            out = out.at[:, i].set(nxt)
            return (i + 1, nxt, key, out, tuple(fixed))

        carry = (jnp.int32(0), tokens, key, out0, cache_arrays)
        _i, tokens, key, out, cache_arrays = lax.while_loop(
            lambda c: c[0] < blk, body, carry)
        return out, tokens, key, list(cache_arrays)

    def _decode_pure(self, *flat):
        n = len(self._state_t)
        state = flat[:n]
        tokens, key, active = flat[n:n + 3]
        cache_arrays = flat[n + 3:]
        return self._masked_step(state, tokens, key, active,
                                 cache_arrays)

    # ---------------- host-side slot management ----------------------
    def submit(self, input_ids, max_new_tokens, request_id=None,
               priority=0, deadline_s=None, ttft_deadline_s=None):
        """Queue one request (1D token list/array). Returns its id.

        deadline_s / ttft_deadline_s bound the request's TOTAL and
        time-to-first-token wall time (defaults from the session);
        expiry evicts the request with state TIMED_OUT instead of
        letting it wait forever. With a bounded queue (``max_queue``)
        an overloaded session sheds load: the configured policy either
        raises :class:`AdmissionRejected` here (fast rejection — the
        request never waits) or, under the ``priority`` policy, evicts
        a lower-priority queued request (delivered as REJECTED)."""
        ids = np.asarray(
            input_ids._data if isinstance(input_ids, Tensor)
            else input_ids).reshape(-1).astype(np.int32)
        if ids.size + max_new_tokens - 1 > self._max_length:
            raise ValueError(
                f"prompt ({ids.size}) + {max_new_tokens} new tokens "
                f"exceeds the cache capacity {self._max_length}")
        if request_id is not None:
            if request_id in self._used_rids:
                raise ValueError(
                    f"request_id {request_id!r} is already in use")
            rid = request_id
        else:
            while self._next_rid in self._used_rids:
                self._next_rid += 1
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(
            rid, ids, ids.size, max_new_tokens, priority=priority,
            deadline_s=(deadline_s if deadline_s is not None
                        else self._default_deadline_s),
            ttft_deadline_s=(ttft_deadline_s if ttft_deadline_s
                             is not None else self._default_ttft_s))
        try:
            victim = self._admission.admit(self._queue, req,
                                           free_slots=len(self._free))
        except AdmissionRejected:
            # shed-not-collapse: the rejection is the fast path — no
            # rid is consumed, nothing is retained
            if _met._ENABLED:
                _met.REGISTRY.counter("serving.rejected").inc()
            raise
        self._used_rids.add(rid)
        if victim is not None:
            self._finish(victim, RequestState.REJECTED)
        self._queue.append(req)
        if _met._ENABLED:
            r = _met.REGISTRY
            r.counter("serving.requests_submitted").inc()
            r.gauge("serving.queue_depth").set(len(self._queue))
            r.gauge("serving.inflight_requests").set(
                len(self._used_rids))
        return rid

    def cancel(self, request_id):
        """Cancel a queued or running request: it transitions to
        CANCELLED, its slot (if any) is freed for the next admission,
        and its partial output is delivered with the next drain.
        Returns True if the request was found in a non-terminal state
        (unknown / already-terminal ids return False)."""
        for req in self._queue:
            if req.rid == request_id:
                self._queue.remove(req)
                self._finish(req, RequestState.CANCELLED)
                return True
        for req in list(self._running.values()):
            if req.rid == request_id:
                self._finish(req, RequestState.CANCELLED)
                return True
        return False

    def status(self, request_id):
        """RequestState of an in-flight or undelivered request; None
        for unknown (or already-delivered) ids."""
        for req in self._queue:
            if req.rid == request_id:
                return req.state
        for req in self._running.values():
            if req.rid == request_id:
                return req.state
        req = self._done.get(request_id)
        return req.state if req is not None else None

    # -------- lifecycle internals (state machine + recovery) ---------
    def _finish(self, req, state, error=None):
        """The single terminal transition: free the slot, record the
        state, park the request for delivery, tick the outcome
        counter. Every exit path — retire, timeout, cancel, shed,
        quarantine — funnels through here."""
        if req.slot is not None:
            self._running.pop(req.slot, None)
            self._free.append(req.slot)
            req.slot = None
        req.state = state
        req.error = error
        self._done[req.rid] = req
        if _met._ENABLED:
            r = _met.REGISTRY
            if state is RequestState.DONE:
                r.counter("serving.requests_completed").inc()
                r.histogram("serving.request_latency_s").observe(
                    time.perf_counter() - req.t_submit)
            elif state is RequestState.TIMED_OUT:
                r.counter("serving.timed_out").inc()
            elif state is RequestState.CANCELLED:
                r.counter("serving.cancelled").inc()
            elif state is RequestState.REJECTED:
                r.counter("serving.rejected").inc()
            elif state is RequestState.FAILED:
                r.counter("serving.quarantined").inc()

    def _expire_deadlines(self):
        """Evict deadline-exceeded requests (queued AND running) —
        runs at the top of every step, so expiry is honored within one
        step of the deadline instant."""
        if not (self._queue or self._running):
            return
        now = time.perf_counter()
        for req in [r for r in self._queue if r.deadline_hit(now)]:
            self._queue.remove(req)
            self._finish(req, RequestState.TIMED_OUT)
        for req in list(self._running.values()):
            if req.deadline_hit(now):
                self._finish(req, RequestState.TIMED_OUT)

    def _health_report(self):
        """Readiness provider for the /healthz endpoint: a non-empty
        reason list means degraded (503)."""
        if getattr(self, "_closed", False):
            return None
        return self._admission.degraded_reasons(
            len(self._queue), len(self._free))

    def _device_call(self, site, ctx, fn, retries=None):
        """Retry-with-backoff envelope around one device dispatch.
        The chaos hook sits INSIDE the try so injected faults exercise
        the same recovery as real ones. Retrying is safe here because
        a dispatch that raised did not consume its donated buffers —
        the session state the closure captured is still alive."""
        retries = self._step_retries if retries is None else retries
        delay = self._step_backoff_s
        attempt = 0
        while True:
            try:
                _chaos.hit(site, **ctx)
                return fn()
            except Exception:
                if attempt >= retries:
                    raise
                attempt += 1
                if _met._ENABLED:
                    _met.REGISTRY.counter("serving.step_retries").inc()
                if delay > 0:
                    time.sleep(delay)
                    delay *= 2

    def _dispatch_once(self, state, slots, retries=None):
        """One decode dispatch for the given active-slot subset; on
        success the sampled tokens are committed to pending tagged
        with exactly that subset (drains credit only those slots)."""
        active = np.zeros((self._slots,), bool)
        active[list(slots)] = True

        def call():
            if self._decode_block:
                return self._decode_blk_jit(
                    *state, self._tokens, self._key,
                    jnp.asarray(active), *self._cache_arrays)
            return self._decode_jit(
                *state, self._tokens, self._key, jnp.asarray(active),
                *self._cache_arrays)

        out = self._device_call("serving.decode_step",
                                {"slots": slots}, call, retries)
        if self._decode_block:
            blk_out, self._tokens, self._key, self._cache_arrays = out
            self._pending.append(("block", slots, blk_out))
        else:
            self._tokens, self._key, self._cache_arrays = out
            self._pending.append(("step", slots, self._tokens))

    def _probe_slots(self, state, subset):
        """Single-attempt step over a slot subset. A SUCCESSFUL probe
        is a real step — its tokens are committed and delivered — so
        bisection never wastes device work or skips tokens. Returns
        True when the subset still fails."""
        try:
            self._dispatch_once(state, tuple(subset), retries=0)
            return False
        except Exception:
            return True

    def _bisect_poison(self, state, slots, exc):
        """Find the single poison slot by probing halves. Returns the
        slot, or None when every probe succeeded (the fault cleared —
        all slots stepped during recovery). Raises ServingStepError
        when DISJOINT subsets fail: that is a step-wide fault, not a
        poison request, and pretending otherwise would quarantine
        innocent requests one by one."""
        while len(slots) > 1:
            mid = len(slots) // 2
            left, right = slots[:mid], slots[mid:]
            lf = self._probe_slots(state, left)
            rf = self._probe_slots(state, right)
            if lf and rf:
                raise ServingStepError(
                    "decode step fails for disjoint slot subsets "
                    f"{tuple(left)} and {tuple(right)} — failure is "
                    "step-wide, not attributable to one poison "
                    "request") from exc
            if lf:
                slots = left
            elif rf:
                slots = right
            else:
                return None
        return slots[0]

    def _recover_decode(self, state, slots, exc):
        """Persistent step failure (retry budget exhausted): isolate
        the poison request by bisection and fail ONLY it; the session
        and every other in-flight request stay alive. The freed slot
        returns to the pool (its cache region is reset by the next
        admission's prefill)."""
        if len(slots) == 1:
            poison = slots[0]
        else:
            poison = self._bisect_poison(state, list(slots), exc)
            if poison is None:
                return
        req = self._running.get(poison)
        if req is not None:
            self._finish(req, RequestState.FAILED,
                         error=f"{type(exc).__name__}: {exc}")

    def _admit_ready(self):
        state = [t._data for t in self._state_t]
        t_admit = time.perf_counter()
        while self._free and self._queue:
            req = self._queue.popleft()
            slot = self._free.pop()
            req.state = RequestState.PREFILLING
            bucket = next((b for b in self._buckets
                           if b >= req.plen), self._max_length)
            padded = jnp.asarray(
                np.pad(req.ids, (0, bucket - req.plen))[None])

            def call():
                return self._admit_jit(
                    *state, padded, jnp.int32(req.plen),
                    jnp.int32(slot), self._tokens, self._key,
                    *self._cache_arrays)

            try:
                self._tokens, self._key, self._cache_arrays = \
                    self._device_call("serving.admit_step",
                                      {"rid": req.rid, "slot": slot},
                                      call)
            except Exception as e:  # noqa: BLE001
                # the failing request is identified directly here (the
                # admit is b=1): quarantine it, keep admitting others
                self._free.append(slot)
                self._finish(req, RequestState.FAILED,
                             error=f"{type(e).__name__}: {e}")
                continue
            req.slot = slot
            req.state = RequestState.DECODING
            self._running[slot] = req
            if _met._ENABLED:
                r = _met.REGISTRY
                r.counter("serving.admits").inc()
                r.counter("serving.prefill_tokens").inc(req.plen)
                dt = time.perf_counter() - t_admit
                if dt > 0:
                    # dispatch-side rate: prefill programs are async,
                    # so this tracks admission throughput, not device
                    # occupancy
                    r.gauge("serving.prefill_tokens_per_s").set(
                        req.plen / dt)
                t_admit = time.perf_counter()
            # the admit's sampled token is the request's first output;
            # it stays ON DEVICE and is fetched with the next pending
            # drain (an immediate device_get would reintroduce one
            # blocking RTT per admission — the cost sync_every exists
            # to amortize). The tagged entry applies to THIS slot only:
            # the other lanes of the vector hold already-consumed
            # decode tokens.
            self._pending.append(("admit", slot, self._tokens))

    def _maybe_retire(self, req):
        if (len(req.tokens) >= req.budget
                or (self._eos is not None
                    and req.tokens
                    and req.tokens[-1] == self._eos)):
            self._finish(req, RequestState.DONE)

    def _drain_pending(self):
        if not self._pending:
            return
        entries = self._pending
        self._pending = []
        _chaos.hit("serving.drain", n=len(entries))
        fetched = jax.device_get([t for (_k, _s, t) in entries])
        delivered = 0
        for (kind, ainfo, _t), row in zip(entries, fetched):
            # ainfo: the admitted slot ("admit") or the tuple of slots
            # active AT DISPATCH ("step"/"block") — only those lanes
            # carry live tokens; slots evicted (cancel/timeout/
            # quarantine) between dispatch and drain are skipped, and
            # recovery probes over subsets credit exactly their subset
            row = np.asarray(row)
            if kind == "admit":
                req = self._running.get(ainfo)
                if req is not None:
                    req.tokens.append(int(row[ainfo]))
                    delivered += 1
                    self._maybe_retire(req)
                continue
            if kind == "block":
                for col in range(row.shape[1]):
                    for slot in ainfo:
                        req = self._running.get(slot)
                        if req is not None:
                            req.tokens.append(int(row[slot, col]))
                            delivered += 1
                            self._maybe_retire(req)
                continue
            for slot in ainfo:
                req = self._running.get(slot)
                if req is not None:
                    req.tokens.append(int(row[slot]))
                    delivered += 1
                    self._maybe_retire(req)
        if _met._ENABLED and delivered:
            now = time.perf_counter()
            r = _met.REGISTRY
            r.counter("serving.decode_tokens").inc(delivered)
            if self._t_last_drain is not None and now > self._t_last_drain:
                r.gauge("serving.decode_tokens_per_s").set(
                    delivered / (now - self._t_last_drain))
            self._t_last_drain = now

    def step(self):
        """Expire deadlines, admit whatever fits (on sync boundaries),
        run ONE batched decode step under the retry/recovery envelope,
        and — every `sync_every` steps — fetch the pending token block
        and retire finished requests. Returns the list of request ids
        that reached a terminal state during this step."""
        before = set(self._done)
        self._expire_deadlines()
        if not self._pending:
            self._admit_ready()
        if _met._ENABLED:
            r = _met.REGISTRY
            r.counter("serving.steps").inc()
            r.gauge("serving.queue_depth").set(len(self._queue))
            r.gauge("serving.slots_active").set(len(self._running))
            r.gauge("serving.slot_utilization").set(
                len(self._running) / self._slots)
            r.gauge("serving.degraded").set(
                1.0 if self._health_report() else 0.0)
        if self._running:
            state = [t._data for t in self._state_t]
            slots = tuple(sorted(self._running))
            try:
                self._dispatch_once(state, slots)
            except ServingStepError:
                raise
            except Exception as e:  # noqa: BLE001
                self._recover_decode(state, slots, e)
        if len(self._pending) >= self._sync_every or (
                self._pending and not self._running):
            # the second arm flushes a PARTIAL sync window when no slot
            # is decoding anymore (every running request was cancelled/
            # timed out/quarantined mid-window): admission is gated on
            # an empty pending list, so waiting out the quantum would
            # deadlock step()/results() with work still queued
            self._drain_pending()
        return [r for r in self._done if r not in before]

    def results(self):
        """Drive the session until every submitted request reaches a
        terminal state, then deliver {rid: RequestResult} — terminal
        state, prompt + generated ids (partial for TIMED_OUT /
        CANCELLED / FAILED), and the error string for FAILED.
        Delivered results are released exactly like :meth:`run`."""
        while self._queue or self._running or self._pending:
            self.step()
        out = {rid: RequestResult(
                   req.state,
                   np.concatenate([req.ids,
                                   np.asarray(req.tokens, np.int32)]),
                   req.error)
               for rid, req in self._done.items()}
        self._done = {}
        # delivered ids leave the in-flight set: a serving loop calling
        # submit()/run() forever must not accumulate every rid ever seen
        self._used_rids.difference_update(out)
        if _met._ENABLED:
            _met.REGISTRY.gauge("serving.inflight_requests").set(
                len(self._used_rids))
        return out

    def run(self):
        """Drain queue + running slots; returns {rid: full token ids}
        (prompt + generated, eos included when emitted) for requests
        completed by THIS drain (or still undelivered from step()
        calls). Requests that ended TIMED_OUT / CANCELLED / FAILED /
        REJECTED deliver their partial ids here — use :meth:`results`
        for the terminal states. Delivered results are released — a
        later run() never re-delivers them, their request_ids become
        reusable, and neither _done nor _used_rids grows unboundedly
        in a long-lived serving session."""
        return {rid: res.ids for rid, res in self.results().items()}

    def close(self):
        """Cancel in-flight work, then release shared resources.
        Queued and running requests transition to CANCELLED (their
        pending device futures are dropped — nothing waits on the
        device, so close never hangs), undelivered results are
        discarded, and ``_used_rids`` ends empty. Idempotent; also
        runs via the context-manager exit and the finalizer."""
        if getattr(self, "_closed", False):
            return
        for req in list(getattr(self, "_queue", ())):
            self._finish(req, RequestState.CANCELLED)
        if getattr(self, "_queue", None) is not None:
            self._queue.clear()
        for req in list(getattr(self, "_running", {}).values()):
            self._finish(req, RequestState.CANCELLED)
        self._pending = []
        self._done = {}
        if getattr(self, "_used_rids", None) is not None:
            self._used_rids.clear()
        if getattr(self, "_health_unreg", None) is not None:
            self._health_unreg()
            self._health_unreg = None
        super().close()

    def executable_counts(self):
        """(n_admit_executables, n_decode_executables): admit is
        bounded by the bucket count, decode must stay 1 however many
        requests flow through (in block mode the block program is THE
        decode executable)."""
        n_dec = self._decode_jit._cache_size()
        if self._decode_block:
            n_dec += self._decode_blk_jit._cache_size()
        return (self._admit_jit._cache_size(), n_dec)



def cached_generate(model, input_ids, max_new_tokens=16, temperature=0.0,
                    top_p=None, seed=None, max_length=None, seq_ceiling=None,
                    hard_limit=False, decode_block=None):
    """Shared model.generate() implementation: pick a cache capacity
    (next power of two covering prompt+new, floored at 64), cache one
    DecodeSession per (capacity, sampling config) on the model, and
    generate.

    seq_ceiling: the model's positional limit. With hard_limit=True
    (learned position tables — GPT's wpe) requests past the ceiling
    raise; with hard_limit=False (RoPE — llama) the ceiling is only a
    sizing hint and longer requests are allowed.
    """
    need = input_ids.shape[1] + max_new_tokens
    if hard_limit and seq_ceiling is not None and need > seq_ceiling:
        raise ValueError(
            f"prompt + max_new_tokens = {need} exceeds the model's "
            f"positional table ({seq_ceiling})")
    ceil_eff = seq_ceiling if (hard_limit and seq_ceiling) else \
        max(seq_ceiling or 0, need)
    cap = max_length or min(max(64, 1 << (need - 1).bit_length()),
                            ceil_eff)
    key = (cap, float(temperature), top_p, decode_block)
    sessions = model.__dict__.setdefault("_decode_sessions", {})
    if key not in sessions:
        sessions[key] = DecodeSession(model, cap, temperature=temperature,
                                      top_p=top_p,
                                      decode_block=decode_block)
    return sessions[key].generate(input_ids, max_new_tokens, seed=seed)
