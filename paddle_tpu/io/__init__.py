"""paddle.io equivalent: Dataset / DataLoader / samplers (reference:
python/paddle/io/reader.py:262, io/dataloader/dataloader_iter.py:155,370).

Round-1 design: in-process iterator with background-thread prefetch to
device (the reference's multiprocess shared-mem workers + C++
LoDTensorBlockingQueue become a thread + queue here; a native C++ loader is
the planned upgrade — TPU input pipelines are host-CPU bound, not
GIL-bound, for tensor collation via numpy).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Iterable, List, Optional

import numpy as np

from paddle_tpu.core import generator as gen_mod
from paddle_tpu.core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumsizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumsizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cumsizes, idx, side="right"))
        prev = self.cumsizes[di - 1] if di > 0 else 0
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        counts = [int(np.floor(total * l)) for l in lengths]
        rem = total - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total)
    out = []
    off = 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


# ------------------------------- samplers ----------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Sample randomly (without replacement) from a fixed index subset
    (reference io/sampler.py SubsetRandomSampler)."""

    def __init__(self, indices):
        if len(indices) == 0:
            raise ValueError(
                "The length of `indices` in SubsetRandomSampler should "
                "be greater than 0.")
        self.indices = list(indices)

    def __iter__(self):
        perm = np.random.permutation(len(self.indices))
        return iter(self.indices[i] for i in perm)

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference:
    io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from paddle_tpu import distributed as dist
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None \
            else dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# ------------------------------ collation ----------------------------------
def _stack(arrs):
    # native threaded collation when available (C++ DataFeed analog)
    try:
        from paddle_tpu import native
        if native.available() and len(arrs) > 1 and arrs[0].nbytes > 4096:
            return native.collate(arrs)
    except Exception:
        pass
    return np.stack(arrs)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(_stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(_stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _produce(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            if self.batch_size is None:
                yield from (self.collate_fn([s]) for s in it)
                return
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
        else:
            for batch_idx in self.batch_sampler:
                yield self.collate_fn(
                    [self.dataset[i] for i in batch_idx])

    def __iter__(self):
        if not self.use_buffer_reader or self.num_workers == 0:
            yield from self._produce()
            return
        # background-thread prefetch (buffered reader / blocking-queue role)
        q: "queue.Queue" = queue.Queue(
            maxsize=max(2, self.prefetch_factor * max(self.num_workers, 1)))
        stop = object()

        def worker():
            try:
                for item in self._produce():
                    q.put(item)
            finally:
                q.put(stop)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            yield item


def get_worker_info():
    return None
