"""paddle.io equivalent: Dataset / DataLoader / samplers (reference:
python/paddle/io/reader.py:262, io/dataloader/dataloader_iter.py:155,370).

Loading paths, mirroring the reference's single/multi-process split:
- num_workers 0/1: in-process iterator, optional background-thread
  prefetch (the C++ LoDTensorBlockingQueue role).
- num_workers > 1 (map-style): forked worker processes pull index
  batches and collate to numpy; the parent reorders for sampler
  determinism and re-wraps on device. Workers are deliberately
  jax-free (the XLA runtime is fork-unsafe), so items cross as numpy —
  the reference's shared-memory discipline, pickled here.
Native C++ helpers (paddle_tpu.native): threaded collate +
uint8-HWC→f32-CHW batch transform feed the same pipeline.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Iterable, List, Optional

import numpy as np

from paddle_tpu.core import generator as gen_mod
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.observability import metrics as _met


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumsizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumsizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cumsizes, idx, side="right"))
        prev = self.cumsizes[di - 1] if di > 0 else 0
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        counts = [int(np.floor(total * l)) for l in lengths]
        rem = total - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total)
    out = []
    off = 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


# ------------------------------- samplers ----------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None, seed=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        #: seeded mode: epoch `e`'s draw is a pure function of
        #: (seed, e) — the restorable-position contract DataLoader
        #: resume relies on (the DistributedBatchSampler idiom).
        #: seed=None keeps the legacy global-RNG behavior.
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch):
        self.epoch = int(epoch)

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.seed is not None:
            rng = np.random.RandomState(
                (int(self.seed) + 1000003 * self.epoch) % (2 ** 32))
            if self.replacement:
                return iter(rng.randint(0, n, self.num_samples).tolist())
            return iter(rng.permutation(n)[: self.num_samples].tolist())
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Sample randomly (without replacement) from a fixed index subset
    (reference io/sampler.py SubsetRandomSampler)."""

    def __init__(self, indices):
        if len(indices) == 0:
            raise ValueError(
                "The length of `indices` in SubsetRandomSampler should "
                "be greater than 0.")
        self.indices = list(indices)

    def __iter__(self):
        perm = np.random.permutation(len(self.indices))
        return iter(self.indices[i] for i in perm)

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def set_epoch(self, epoch):
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference:
    io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from paddle_tpu import distributed as dist
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None \
            else dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# ------------------------------ collation ----------------------------------
def _stack(arrs):
    # native threaded collation when available (C++ DataFeed analog)
    try:
        from paddle_tpu import native
        if native.available() and len(arrs) > 1 and arrs[0].nbytes > 4096:
            return native.collate(arrs)
    except Exception:
        pass
    return np.stack(arrs)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(_stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(_stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, seed=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if seed is not None and (self._iterable_mode
                                 or batch_sampler is not None
                                 or batch_size is None):
            # seed only governs the loader-BUILT sampler; silently
            # storing it next to an external/iterable ordering would
            # let a resume fast-forward a permutation the seed never
            # produced (claiming exact replay while corrupting order)
            raise ValueError(
                "DataLoader(seed=...) requires the loader-built batch "
                "sampler (map-style dataset, batch_size set, no "
                "external batch_sampler) — an external sampler owns "
                "its ordering and must carry its own seed/epoch state")
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
        else:
            sampler = RandomSampler(dataset, seed=seed) \
                if (shuffle and seed is not None) else None
            self.batch_sampler = BatchSampler(
                dataset, sampler=sampler, shuffle=shuffle,
                batch_size=batch_size, drop_last=drop_last)
        # resumable position (ISSUE 15): with `seed` set, the shuffle
        # order is a pure function of (seed, epoch) and the loader's
        # position is three ints — what preemption-safe checkpoints
        # capture so a resume replays the exact data order.
        self._seed = seed
        self._epoch = 0
        self._batches_served = 0
        self._skip_next = 0
        self._auto_epoch = (batch_sampler is None
                            and self.batch_sampler is not None)

    # ---------------------------------------------- resumable position
    def set_epoch(self, epoch):
        self._epoch = int(epoch)

    def state_dict(self):
        """Loader position for preemption-safe checkpoints: epoch,
        batches already CONSUMED this epoch, and the shuffle seed."""
        return {"epoch": int(self._epoch),
                "batches_served": int(self._batches_served),
                "seed": self._seed}

    def set_state_dict(self, state):
        saved_seed = state.get("seed")
        if saved_seed != self._seed:
            # EITHER direction (including seed=None on one side): a
            # position under one shuffle order is meaningless under
            # another — silently fast-forwarding a different
            # permutation would re-train some samples and skip others
            # while claiming exact resume
            raise ValueError(
                f"DataLoader resume: checkpoint shuffle seed "
                f"{saved_seed!r} != this loader's seed {self._seed!r} "
                "— the saved data order cannot be replayed")
        self._epoch = int(state.get("epoch", 0))
        self._skip_next = int(state.get("batches_served", 0))
        self._batches_served = self._skip_next
        if self._skip_next and self._seed is None and self._auto_epoch \
                and isinstance(getattr(self.batch_sampler, "sampler",
                                       None), RandomSampler):
            import warnings
            warnings.warn(
                "DataLoader resume with unseeded shuffle: the position "
                "is restored but the permutation is not reproducible — "
                "pass DataLoader(..., seed=N) for exact data-order "
                "replay", stacklevel=2)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _produce(self, skip=0):
        # skip: batches already consumed before a resume. Index-driven
        # modes fast-forward WITHOUT loading the skipped samples;
        # iterable datasets must consume (and drop) them.
        if self._iterable_mode:
            it = iter(self.dataset)
            if self.batch_size is None:
                for _ in itertools.islice(it, skip):
                    pass
                yield from (self.collate_fn([s]) for s in it)
                return
            while skip > 0:
                if not list(itertools.islice(it, self.batch_size)):
                    return
                skip -= 1
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(skip, len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
        else:
            for batch_idx in self.batch_sampler:
                if skip > 0:
                    skip -= 1
                    continue
                yield self.collate_fn(
                    [self.dataset[i] for i in batch_idx])

    def __iter__(self):
        # epoch sync + fast-forward happen here (once per pass), so
        # every loading mode shares the resume semantics; position is
        # counted at CONSUMPTION (prefetch queues may hold more)
        if self._auto_epoch and hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(self._epoch)
        skip = self._skip_next
        self._skip_next = 0
        self._batches_served = skip
        inner = self._iter_batches(skip)
        if not _met._ENABLED:
            for item in inner:
                self._batches_served += 1
                yield item
            self._epoch += 1
            self._batches_served = 0
            return
        # fetch-wait accounting: how long the consumer (the train loop)
        # blocks per batch — the input-pipeline stall signal. Covers
        # every loading mode since it wraps the mode dispatch.
        hist = _met.REGISTRY.histogram("dataloader.fetch_wait_s")
        batches = _met.REGISTRY.counter("dataloader.batches")
        import time as _time
        while True:
            t0 = _time.perf_counter()
            try:
                item = next(inner)
            except StopIteration:
                self._epoch += 1
                self._batches_served = 0
                return
            hist.observe(_time.perf_counter() - t0)
            batches.inc()
            self._batches_served += 1
            yield item

    def _iter_batches(self, skip=0):
        if not self.use_buffer_reader or self.num_workers == 0:
            yield from self._produce(skip)
            return
        if not self._iterable_mode and self.batch_sampler is not None \
                and self.num_workers > 1:
            yield from self._iter_multiprocess(skip)
            return
        # background-thread prefetch (buffered reader / blocking-queue role)
        q: "queue.Queue" = queue.Queue(
            maxsize=max(2, self.prefetch_factor * max(self.num_workers, 1)))
        stop = object()
        abandoned = threading.Event()

        def _put(item) -> bool:
            # bounded-blocking put that gives up when the consumer
            # abandoned the iterator (mid-epoch preemption / crash):
            # a worker stuck forever on q.put would leak one thread
            # plus its buffered batches per crashed attempt
            while not abandoned.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    pass
            return False

        def worker():
            try:
                for item in self._produce(skip):
                    if not _put(item):
                        return
            finally:
                _put(stop)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is stop:
                    break
                yield item
        finally:
            abandoned.set()

    # ----------------------------------------------------------------
    # True multi-process loading (reference
    # io/dataloader/dataloader_iter.py:370 _DataLoaderIterMultiProcess:
    # worker processes pull index batches, collate to numpy, push
    # results; the parent reorders to keep sampler determinism).
    # ----------------------------------------------------------------
    def _iter_multiprocess(self, skip=0):
        import multiprocessing as mp
        ctx = mp.get_context("fork")
        n = self.num_workers
        idx_queues = [ctx.Queue() for _ in range(n)]
        out_q = ctx.Queue(maxsize=max(2, self.prefetch_factor * n))
        timeout = self.timeout if getattr(self, "timeout", 0) else 120

        procs = [ctx.Process(
            target=_worker_loop,
            args=(self.dataset, self.collate_fn, idx_queues[w], out_q,
                  w, n, self.worker_init_fn),
            daemon=True) for w in range(n)]
        for p in procs:
            p.start()
        try:
            batches = list(self.batch_sampler)[skip:]
            for seq, b in enumerate(batches):
                idx_queues[seq % n].put((seq, list(b)))
            for iq in idx_queues:
                iq.put(None)
            pending = {}
            want = 0
            got = 0
            while got < len(batches):
                if want in pending:
                    item = pending.pop(want)
                else:
                    seq, payload = out_q.get(timeout=timeout)
                    if seq == -1:
                        raise RuntimeError(
                            f"DataLoader worker failed: {payload}")
                    if seq != want:
                        pending[seq] = payload
                        continue
                    item = payload
                got += 1
                want += 1
                yield _rewrap(item)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)


class WorkerInfo:
    """reference io/dataloader/worker.py WorkerInfo."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info: Optional[WorkerInfo] = None


def _unwrap(item):
    """Tensor -> numpy for the queue (device handles don't cross
    processes)."""
    if isinstance(item, Tensor):
        return ("__t__", item.numpy())
    if isinstance(item, (list, tuple)):
        return type(item)(_unwrap(i) for i in item)
    if isinstance(item, dict):
        return {k: _unwrap(v) for k, v in item.items()}
    return item


def _rewrap(item):
    if isinstance(item, tuple) and len(item) == 2 and item[0] == "__t__":
        return Tensor(item[1])
    if isinstance(item, (list, tuple)):
        return type(item)(_rewrap(i) for i in item)
    if isinstance(item, dict):
        return {k: _rewrap(v) for k, v in item.items()}
    return item


def _np_collate(batch):
    """numpy-only collate for worker processes — forked children must
    never touch the (fork-unsafe) jax runtime; the parent re-wraps."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return ("__t__", np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return ("__t__", np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return ("__t__", np.asarray(batch, np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [_np_collate(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate {type(sample)}")


def _worker_loop(dataset, collate_fn, idx_q, out_q, worker_id,
                 num_workers, worker_init_fn):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    np_mode = collate_fn is default_collate_fn
    try:
        while True:
            job = idx_q.get()
            if job is None:
                break
            seq, indices = job
            items = [dataset[i] for i in indices]
            if np_mode:
                out = _np_collate(items)
            else:
                # custom collate: must stay numpy-only in workers (the
                # jax runtime is fork-unsafe); Tensors are unwrapped
                out = _unwrap(collate_fn(items))
            out_q.put((seq, out))
    except Exception as e:  # surface the error to the parent
        out_q.put((-1, f"worker {worker_id}: {e!r}"))


def get_worker_info():
    """Inside a worker process: (id, num_workers, dataset); None in the
    main process (reference io/dataloader/worker.py get_worker_info)."""
    return _worker_info
